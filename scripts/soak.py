#!/usr/bin/env python
"""Randomized cross-backend byte-compare soak (CPU mesh, offline).

Samples random (backend, storage, boundary, mesh, filter, fuse, tile,
interior_split, geometry) configurations and requires every one to be
byte-identical to the NumPy oracle through the full distributed path
(`step.sharded_iterate` on the forced 8-virtual-device CPU mesh).  This
is the tests' bit-exactness property run at campaign scale — the seeded
pytest fuzzes keep the suite fast; this script converts idle wall-clock
(e.g. a dead TPU tunnel) into verification depth.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python scripts/soak.py --n 64 --seed 0

One JSON row per config (failures carry the config verbatim), one
summary row at the end; exit 0 iff every config matched.

``--faults N`` is the resilience soak: N random fault plans
(resilience.faults specs — checkpoint tears, compile/exchange crashes)
are sampled and each trial runs as a leg of the supervised runner
(resilience.supervisor) in a clean 8-virtual-device CPU child: inject →
crash mid-checkpointed-run → resume clean → byte-compare against the
oracle.  Idle wall-clock (a dead TPU tunnel) thereby exercises the
recovery paths, not just the happy path:

  python scripts/soak.py --faults 16 --seed 0

``--serve --faults N`` runs the same drill through the SERVING engine
(round 8): each trial installs a random compile/exchange fault plan,
pushes a burst of requests through an in-process ConvolutionService —
whose with_retry/degradation wiring must heal the injected faults into
byte-identical responses — then simulates the restart (plan uninstalled,
probe cache cleared, fresh service) and requires clean service.  This
extends ``PCTPU_FAULTS`` coverage to the serving layer:

  python scripts/soak.py --serve --faults 8 --seed 0

``--reshape N`` is the ELASTIC-RECOVERY drill (round 10): each trial
crashes a checkpointed run on the 2x4 CPU mesh at a random injected
fault site, then resumes the crash's snapshot dir on each of the 1x2,
2x2, and 1x1 meshes — the checkpoint resharding path — and requires
every resumed output byte-identical to the single-device oracle.
Trials run as supervised legs like ``--faults``; ``--summary-out``
lands the summary row in a file (the ``--elastic-smoke`` tier-1 leg's
done_file):

  python scripts/soak.py --reshape 8 --seed 0

``--router-kill N`` is the REPLICA-ROUTER drill (round 14): boot three
in-process replicas behind ``serving.router.ReplicaRouter``, push
continuous traffic while killing and reviving one replica per cycle
(N cycles), and require ZERO non-rejected failures with every completed
response byte-identical to the oracle — the serve-through-any-single-
replica-failure property, plus at least one client-observed failover:

  python scripts/soak.py --router-kill 3 --seed 0

``--chaos N`` is the CHAOS-TRANSPORT drill (round 18): three in-process
replicas behind the durable router, every transport wrapped in
``serving.chaos.ChaosTransport``; each of the N cycles samples a seeded
transport-fault schedule (send drops/latency/black-holes, lost and
corrupt responses, flapping readiness, mid-stream disconnects) and
drives mixed batch + converge traffic through it, killing the serving
replica mid-stream on even cycles.  Gates per run: zero non-rejected
failures, every completed batch response AND converge final row
byte-identical to the uninterrupted oracle, >= 1 mid-stream resume
observed, exactly one final row per request_id:

  python scripts/soak.py --chaos 4 --seed 0

``--router-restart N`` is the CRASH-SAFE CONTROL-PLANE drill (round
19): one WAL lineage, N router lives.  Each cycle constructs a fresh
``ReplicaRouter`` over the SAME WAL (a fenced takeover: the epoch must
strictly increase), finishes the PREVIOUS life's crash-interrupted
converge job via a client retry — which must RESUME from the recovered
ledger token and end byte-identical to the uninterrupted oracle with
exactly one final row per request_id — then starts a new converge job,
crashes the router mid-stream at a seeded ``router_kill`` row, and
verifies the dead life's object is rejected typed ``stale_epoch`` as a
zombie.  A closing extra life drains the last pending job:

  python scripts/soak.py --router-restart 3 --seed 0
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time
from pathlib import Path

import _path  # noqa: F401  (repo root + JAX_PLATFORMS re-apply)

MESH_SHAPES = [(1, 1), (1, 2), (2, 2), (4, 2), (2, 4), (8, 1), (1, 8)]
FILTERS = ["blur3", "box3", "gaussian5", "edge5", "sharpen3", "jacobi3"]
BACKENDS = ["shifted", "pallas", "pallas_sep", "pallas_rdma"]


def sample(rng: random.Random) -> dict:
    backend = rng.choice(BACKENDS)
    cfg = {
        "backend": backend,
        "filter": rng.choice(FILTERS),
        "mesh": rng.choice(MESH_SHAPES),
        "channels": rng.choice([1, 1, 3]),
        "H": rng.randrange(24, 180),
        "W": rng.randrange(24, 180),
        "iters": rng.randrange(1, 6),
        "boundary": rng.choice(["zero", "zero", "periodic"]),
        "storage": rng.choice(["f32", "bf16", "u8"]),
        "fuse": 1,
        "interior_split": False,
        "tile": None,
        "img_seed": rng.randrange(10_000),
    }
    if backend == "pallas_rdma":
        # rdma carries the exchange in-kernel: fuse=1 by design, and the
        # monolithic kernel wants blocks >= a couple of rows; keep the
        # random geometry but a divisible-ish floor on size.
        cfg["H"] = max(cfg["H"], 32)
        cfg["W"] = max(cfg["W"], 32)
        cfg["storage"] = rng.choice(["f32", "bf16"])
    else:
        # step.py clamps fuse to min(fuse, iters); record the EFFECTIVE
        # value so the evidence rows state what actually ran.
        cfg["fuse"] = min(rng.choice([1, 2, 3, 4, 8]), cfg["iters"])
    if backend in ("pallas", "pallas_sep"):
        if rng.random() < 0.3:
            cfg["tile"] = (8 * rng.randrange(1, 4), 128)
        # step.py takes the non-split path under periodic; only record
        # the flag where it is actually exercised.
        if (backend == "pallas_sep" and cfg["fuse"] > 1
                and cfg["boundary"] == "zero" and rng.random() < 0.5):
            cfg["interior_split"] = True
    return cfg


def sample_converge(rng: random.Random) -> dict:
    backend = rng.choice(BACKENDS)
    # The convergence soak compares FLOAT mode (the oracle's
    # run_to_convergence_f32 semantics), and pallas_sep's rank-1 form is
    # documented as "bit-identical in quantize mode, a rounding-order
    # change in float mode" (pallas_stencil) — so pallas_sep draws keep
    # to the non-separable smoother, where it runs the 2D order.
    choices = (["jacobi3"] if backend == "pallas_sep"
               else ["jacobi3", "blur3", "gaussian5"])
    cfg = {
        "mode": "converge",
        "backend": backend,
        # Smoothers, so runs actually converge inside max_iters often;
        # non-convergent draws simply exercise the max_iters exit.
        "filter": rng.choice(choices),
        "mesh": rng.choice(MESH_SHAPES),
        "H": rng.randrange(24, 120),
        "W": rng.randrange(24, 120),
        "tol": rng.choice([0.01, 0.05, 0.2, 0.5]),
        "max_iters": rng.randrange(20, 120),
        "check_every": rng.randrange(1, 11),
        "boundary": rng.choice(["zero", "zero", "periodic"]),
        "fuse": 1 if backend == "pallas_rdma" else rng.choice([1, 2, 4, 8]),
        "img_seed": rng.randrange(10_000),
    }
    # The convergence runner clamps fuse to check_every; record the
    # effective value, as in sample().
    cfg["fuse"] = min(cfg["fuse"], cfg["check_every"])
    if backend == "pallas_rdma":
        cfg["H"] = max(cfg["H"], 32)
        cfg["W"] = max(cfg["W"], 32)
    return cfg


def run_converge(cfg, jax, np, filters, oracle, mesh_lib, step, imageio):
    """C6 soak under the float-mode contract (DESIGN.md bit-exactness
    note): the sampled backend must be BIT-identical (bytes + iteration
    count) to the framework's own `shifted` reference on a different
    mesh — one rounding discipline across compiled backends — and
    ulp-level `allclose` to the two-rounding oracle, whose chained f32
    values legitimately differ once mantissas fill (single-rounding FMA
    vs mul+add).  Iteration counts vs the oracle may differ by at most
    one check chunk (an ulp at the tol threshold flips one check)."""
    filt = filters.get_filter(cfg["filter"])
    img = imageio.generate_test_image(cfg["H"], cfg["W"], "grey",
                                      seed=cfg["img_seed"]).astype(np.float32)
    want, want_iters = oracle.run_to_convergence_f32(
        img, filt, tol=cfg["tol"], max_iters=cfg["max_iters"],
        check_every=cfg["check_every"], boundary=cfg["boundary"])
    mesh = mesh_lib.make_grid_mesh(
        jax.devices()[: cfg["mesh"][0] * cfg["mesh"][1]], cfg["mesh"])
    got, got_iters = step.sharded_converge(
        img[None], filt, tol=cfg["tol"], max_iters=cfg["max_iters"],
        check_every=cfg["check_every"], mesh=mesh, quantize=False,
        backend=cfg["backend"], boundary=cfg["boundary"], fuse=cfg["fuse"])
    got = np.asarray(got)[0]
    ref, ref_iters = step.sharded_converge(
        img[None], filt, tol=cfg["tol"], max_iters=cfg["max_iters"],
        check_every=cfg["check_every"],
        mesh=mesh_lib.make_grid_mesh(jax.devices()[:1], (1, 1)),
        quantize=False, backend="shifted", boundary=cfg["boundary"])
    ref = np.asarray(ref)[0]
    bit_ok = bool(got_iters == ref_iters and np.array_equal(got, ref))
    if got_iters != want_iters:
        # An ulp at the tol threshold legitimately flips one check; the
        # two snapshots are then a chunk apart and differ by up to
        # ~check_every*tol.  Compare value agreement at the SAME
        # iteration count instead.
        want = oracle.run_serial_f32(img, filt, got_iters,
                                     boundary=cfg["boundary"])
    oracle_ok = bool(
        abs(got_iters - want_iters) <= cfg["check_every"]
        and np.allclose(got, want, rtol=0, atol=1e-3))
    row = {"ok": bit_ok and oracle_ok, "bit_vs_shifted_1x1": bit_ok,
           "allclose_vs_oracle": oracle_ok}
    if not row["ok"]:
        row.update(want_iters=want_iters, got_iters=got_iters,
                   ref_iters=ref_iters)
    return row


def run_fault_trial(spec: str, seed: int, out_path: str) -> int:
    """One injected-fault drill: crash a checkpointed run, resume, compare.

    Runs in its own process (the supervised runner spawns it on the
    forced 8-virtual-device CPU mesh) so an injected trace-time fault
    can't poison compilation caches for sibling trials.  Exit 0 iff the
    resumed output is byte-identical to the oracle.
    """
    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.utils import checkpoint, imageio

    rng = random.Random(seed)
    filt = filters.get_filter(rng.choice(["blur3", "gaussian5", "sharpen3"]))
    H, W = rng.randrange(33, 70), rng.randrange(33, 70)
    total, every = rng.randrange(5, 11), rng.randrange(2, 4)
    n_dev = len(jax.devices())
    shape = rng.choice([s for s in [(1, 2), (2, 2), (2, 4)]
                        if s[0] * s[1] <= n_dev] or [(1, 1)])
    mesh = mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)
    img = imageio.generate_test_image(H, W, "grey", seed=seed)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    ck = tempfile.mkdtemp(prefix="pctpu_fault_trial_")

    crashed = None
    with faults.injected(spec, seed=seed) as plan:
        try:
            xs, valid_hw, _ = step._prepare(x, mesh, filt.radius)
            checkpoint.run_checkpointed(xs, filt, total, mesh, valid_hw,
                                        ckpt_dir=ck, every=every)
        except Exception as e:  # noqa: BLE001 — the injected crash
            crashed = repr(e)
        fired = plan.fired
    # The restarted process: fresh input, no plan — must auto-resume from
    # whatever (possibly torn) checkpoint state the crash left behind.
    xs2, valid_hw, _ = step._prepare(x, mesh, filt.radius)
    out = checkpoint.run_checkpointed(xs2, filt, total, mesh, valid_hw,
                                      ckpt_dir=ck, every=every)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    want = oracle.run_serial_u8(img, filt, total)
    ok = bool(np.array_equal(got[0], want))
    row = {
        "ok": ok, "spec": spec, "seed": seed, "crashed": crashed,
        "fired": [list(f) for f in fired], "filter": filt.name,
        "H": H, "W": W, "total": total, "every": every,
        "mesh": "x".join(map(str, shape)),
    }
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    Path(out_path).write_text(json.dumps(row))
    print(json.dumps(row), flush=True)
    return 0 if ok else 1


def run_serve_trial(spec: str, seed: int, out_path: str) -> int:
    """One injected-fault drill through the serving engine.

    Phase 1 (faulted): with ``spec`` installed, a burst of same-key
    requests flows through an in-process ConvolutionService; the engine's
    retry + per-key degradation must turn every injected transient
    compile/exchange fault into a byte-identical response (possibly on a
    degraded effective backend — recorded in the row).
    Phase 2 ("resume"): plan uninstalled and probe cache cleared — the
    fresh-process state after a restart — then a fresh service must serve
    the same key cleanly on the REQUESTED tier.  Exit 0 iff every
    response in both phases is byte-identical to the oracle.
    """
    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.resilience import degrade, faults
    from parallel_convolution_tpu.resilience.retry import RetryPolicy
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request, Response,
    )
    from parallel_convolution_tpu.utils import imageio

    rng = random.Random(seed)
    filt = filters.get_filter(rng.choice(["blur3", "gaussian5", "sharpen3"]))
    H, W = rng.randrange(28, 64), rng.randrange(28, 64)
    iters = rng.randrange(1, 5)
    backend = rng.choice(["shifted", "pallas", "pallas_sep"])
    n_dev = len(jax.devices())
    shape = rng.choice([s for s in [(1, 2), (2, 2), (2, 4)]
                        if s[0] * s[1] <= n_dev] or [(1, 1)])
    mesh = mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)
    img = imageio.generate_test_image(H, W, "grey", seed=seed)
    want = oracle.run_serial_u8(img, filt, iters)
    policy = RetryPolicy(max_attempts=4, base_delay=0.01, max_delay=0.1)

    def burst(svc, n):
        reqs = [svc.submit(Request(image=img, filter_name=filt.name,
                                   iters=iters, backend=backend),
                           wait=False) for _ in range(n)]
        return [s.result(300) if hasattr(s, "result") else s for s in reqs]

    with faults.injected(spec, seed=seed) as plan:
        svc = ConvolutionService(mesh, max_delay_s=0.02, retry_policy=policy)
        faulted = burst(svc, 6)
        svc.close()
        fired = plan.fired
        retries = svc.stats["retries"]
    # The restart: no plan, no cached probe verdicts — a fresh process's
    # serving state, which must come up clean on the requested tier.
    degrade.clear_probe_cache()
    svc2 = ConvolutionService(mesh, max_delay_s=0.02, retry_policy=policy)
    resumed = burst(svc2, 2)
    svc2.close()

    def verdicts(results):
        out = []
        for r in results:
            ok = (isinstance(r, Response)
                  and np.array_equal(np.asarray(r.image), want))
            out.append({
                "ok": bool(ok),
                "effective_backend": getattr(r, "effective_backend", None),
                **({} if ok else {"got": type(r).__name__,
                                  "detail": getattr(r, "detail", "")[:200]}),
            })
        return out

    vf, vr = verdicts(faulted), verdicts(resumed)
    ok = all(v["ok"] for v in vf + vr) and all(
        v["effective_backend"] == backend for v in vr)
    row = {
        "ok": ok, "mode": "serve", "spec": spec, "seed": seed,
        "filter": filt.name, "H": H, "W": W, "iters": iters,
        "backend": backend, "mesh": "x".join(map(str, shape)),
        "fired": [list(f) for f in fired], "retries": retries,
        "faulted_effective": sorted({v["effective_backend"] for v in vf
                                     if v["effective_backend"]}),
        "failures": [v for v in vf + vr if not v["ok"]][:4],
    }
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    Path(out_path).write_text(json.dumps(row))
    print(json.dumps(row), flush=True)
    return 0 if ok else 1


def run_router_kill(args) -> int:
    """Kill/revive drill: 3 in-process replicas behind the router.

    Traffic threads push oracle-checked requests (a few distinct compile
    keys, so routing exercises multiple ring points) while the killer
    thread cycles through replicas: kill → keep serving → revive.  The
    gates, in order of importance:

    1. zero non-rejected failures (retryable sheds are re-driven with
       capped backoff, mirroring loadgen's client contract);
    2. every completed response byte-identical to the NumPy oracle;
    3. with ``N >= 1`` kill cycles, at least one observed failover
       (a request served off its consistent-hash home after a failure).
    """
    import threading

    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio
    import base64

    n_cycles = args.router_kill
    rng = random.Random(args.seed)
    img = imageio.generate_test_image(40, 56, "grey", seed=args.seed)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    # Distinct iteration counts = distinct compile keys = distinct ring
    # points: the kill must be able to hit a key's home replica.
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"), it)
               for it in iters_pool}

    def factory():
        return ConvolutionService(mesh_from_spec("2x2"),
                                  max_delay_s=0.002, max_queue=256)

    replicas = [InProcessReplica(factory, name=f"r{i}") for i in range(3)]
    router = ReplicaRouter(replicas, breaker_threshold=2,
                           breaker_cooldown_s=0.2, poll_interval_s=0.05)
    n_requests = 40 + 20 * n_cycles
    results, lock = [], threading.Lock()
    stop = threading.Event()

    def body_for(i: int) -> dict:
        return {"image_b64": b64, "rows": 40, "cols": 56, "mode": "grey",
                "filter": "blur3", "iters": iters_pool[i % len(iters_pool)],
                "request_id": f"rk{i}"}

    def one(i: int) -> None:
        it = iters_pool[i % len(iters_pool)]
        body = body_for(i)
        for attempt in range(6):
            status, wire = router.request(dict(body), tenant="drill")
            if wire.get("ok") or not wire.get("retryable"):
                break
            time.sleep(min(float(wire.get("retry_after_s") or 0.05), 0.5))
        ok = bool(wire.get("ok"))
        byte_ok = None
        if ok:
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(40, 56)
            byte_ok = bool(np.array_equal(got, oracles[it]))
        with lock:
            results.append({
                "i": i, "ok": ok, "byte_ok": byte_ok,
                "rejected": wire.get("rejected"),
                "retryable": wire.get("retryable"),
                "router": wire.get("router", {}),
            })

    # The kill must be able to CAUSE a failover: victims are the
    # consistent-hash HOME replicas of the live keys, not random picks.
    from parallel_convolution_tpu.serving.router import route_key

    homes = []
    for it in iters_pool:
        cands = router.ring.candidates(route_key(body_for(it)))
        if cands and cands[0] not in homes:
            homes.append(cands[0])

    def traffic() -> None:
        while not stop.is_set():
            with lock:
                i = counter[0]
                if i >= n_requests:
                    return
                counter[0] += 1
            one(i)
            time.sleep(0.01)   # pace: traffic must span the kill cycles

    counter = [0]
    workers = [threading.Thread(target=traffic, daemon=True)
               for _ in range(4)]
    for w in workers:
        w.start()

    kills = []
    for cycle in range(n_cycles):
        time.sleep(0.4)
        victim = homes[cycle % len(homes)]
        router.replica(victim).kill()
        kills.append(victim)
        time.sleep(0.4)
        router.replica(victim).revive()
    for w in workers:
        w.join(300)
    stop.set()
    router.close()

    completed = [r for r in results if r["ok"]]
    byte_fails = [r for r in completed if not r["byte_ok"]]
    non_rejected = [r for r in results
                    if not r["ok"] and not r.get("retryable")]
    # A failover, client-observed: the request completed OFF its
    # consistent-hash home (the dead replica's keys re-homed) or the
    # router reported a failed dispatch before success.
    failovers = sum(
        1 for r in completed
        if r["router"].get("failovers", 0) > 0
        or (r["router"].get("replica") and r["router"].get("home")
            and r["router"]["replica"] != r["router"]["home"]))
    failures = len(byte_fails) + len(non_rejected)
    if n_cycles >= 1 and failovers < 1:
        # the drill exists to prove serve-through-failure: a run where
        # no kill was ever observed proves nothing — fail it loudly.
        failures += 1
    summary = {
        "summary": "router-kill", "n": n_requests, "cycles": n_cycles,
        "seed": args.seed, "kills": kills,
        "completed": len(completed),
        "final_retryable_sheds": sum(1 for r in results
                                     if not r["ok"] and r.get("retryable")),
        "failovers_observed": failovers,
        "byte_mismatches": len(byte_fails),
        "non_rejected_failures": len(non_rejected),
        "failures": failures,
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


def run_chaos_drill(args) -> int:
    """Chaos-transport drill (round 18): mixed traffic under sampled,
    seeded transport-fault schedules + mid-stream kills; see module
    docstring for the gates."""
    import base64

    import numpy as np

    from _chaos_common import (
        chaos_pool, converge_body, oracle_converge_final,
        request_with_backoff,
    )
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.serving.router import ReplicaRouter
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    rng = random.Random(args.seed)
    img = imageio.generate_test_image(40, 56, "grey", seed=args.seed)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(
        img, filters.get_filter("blur3"), it) for it in iters_pool}

    def factory():
        return ConvolutionService(mesh_from_spec("1x2"),
                                  max_delay_s=0.002, max_queue=256)

    def cbody(rid: str) -> dict:
        return converge_body(b64, 40, 56, rid)

    def vbody(rid: str) -> dict:
        # The rank-3 drill body (--volume): a (D,H,W) wave relaxation,
        # small enough that every cycle can afford the stream.
        vol = np.random.default_rng(args.seed).random(
            (2, 4, 16, 16), dtype=np.float32)
        return {"rows": 16, "cols": 16, "depth": 4, "mode": "volume",
                "volume_b64": base64.b64encode(vol.tobytes()).decode(),
                "filter": "wave", "boundary": "periodic", "tol": 0.0,
                "max_iters": 12, "check_every": 4, "request_id": rid}

    try:
        oracle_final = oracle_converge_final(factory, cbody("oracle"))
        vol_oracle = (oracle_converge_final(factory, vbody("oracle-v"))
                      if args.volume else None)
    except RuntimeError as e:
        print(json.dumps({"summary": "chaos", "failures": 1,
                          "detail": str(e)}))
        return 1

    # One replica per failure shape: drops, corrupt bodies, latency.
    reps = chaos_pool(factory, args.seed)
    router = ReplicaRouter(reps, breaker_threshold=3,
                           breaker_cooldown_s=0.2, poll_interval_s=0.05)

    failures: list[str] = []
    resumes = 0
    finals_per_rid: dict[str, int] = {}
    t0 = time.time()
    specs = []
    for cycle in range(args.chaos):
        # A sampled, seeded schedule per cycle — every run replayable.
        parts = [f"transport_stream:{rng.randint(2, 4)}"]
        if rng.random() < 0.7:
            parts.append(f"transport_send:{rng.randint(1, 5)}")
        if rng.random() < 0.7:
            parts.append(f"transport_recv:{rng.randint(2, 6)}")
        if rng.random() < 0.5:
            parts.append("readyz_probe:p0.2")
        spec = ",".join(parts)
        specs.append(spec)
        with faults.injected(spec, seed=args.seed + cycle):
            for i in range(8):
                body = {"image_b64": b64, "rows": 40, "cols": 56,
                        "mode": "grey", "filter": "blur3",
                        "iters": iters_pool[i % 3],
                        "request_id": f"ch{cycle}-{i}"}
                wire = request_with_backoff(router, body)
                if wire.get("ok"):
                    got = np.frombuffer(
                        base64.b64decode(wire["image_b64"]),
                        np.uint8).reshape(40, 56)
                    if not np.array_equal(got, oracles[iters_pool[i % 3]]):
                        failures.append(
                            f"cycle {cycle} req {i}: byte mismatch")
                elif not wire.get("retryable"):
                    failures.append(
                        f"cycle {cycle} req {i}: non-rejected failure "
                        f"{wire.get('rejected')}: "
                        f"{str(wire.get('detail'))[:120]}")
            rid = f"cv{cycle}"
            status, rows = router.converge(cbody(rid))
            it = iter(rows)
            drained = []
            victim = ""
            try:
                first = next(it)
                drained.append(first)
                if cycle % 2 == 0:
                    victim = first.get("router", {}).get("replica", "")
                    if victim:
                        router.replica(victim).kill()
                drained.extend(it)
            except StopIteration:
                pass
            if cycle % 2 == 0 and victim:
                router.replica(victim).revive()
            final = drained[-1] if drained else {}
            for r in drained:
                if r.get("kind") == "final":
                    finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
            if final.get("kind") == "final":
                if final.get("image_b64") != oracle_final["image_b64"]:
                    failures.append(
                        f"cycle {cycle}: converge final not "
                        "byte-identical to oracle")
                if final.get("router", {}).get("resume_count", 0) > 0:
                    resumes += 1
            elif not final.get("retryable"):
                failures.append(
                    f"cycle {cycle}: converge ended non-rejected: "
                    f"{ {k: v for k, v in final.items() if k != 'image_b64'} }")
            if args.volume:
                # Rank-3 drill (round 24): the volume stream rides the
                # SAME cycle schedule; odd cycles kill its replica
                # mid-flight (even cycles killed the 2-D stream's), so
                # the run covers both volume-kill-resume and
                # volume-under-transport-faults.
                vrid = f"vol{cycle}"
                status, vrows = router.converge(vbody(vrid))
                vit = iter(vrows)
                vdrained = []
                vvictim = ""
                try:
                    vfirst = next(vit)
                    vdrained.append(vfirst)
                    if cycle % 2 == 1:
                        vvictim = vfirst.get("router", {}).get(
                            "replica", "")
                        if vvictim:
                            router.replica(vvictim).kill()
                    vdrained.extend(vit)
                except StopIteration:
                    pass
                if vvictim:
                    router.replica(vvictim).revive()
                vfinal = vdrained[-1] if vdrained else {}
                for r in vdrained:
                    if r.get("kind") == "final":
                        finals_per_rid[vrid] = (
                            finals_per_rid.get(vrid, 0) + 1)
                if vfinal.get("kind") == "final":
                    if (vfinal.get("image_b64")
                            != vol_oracle["image_b64"]):
                        failures.append(
                            f"cycle {cycle}: volume final not "
                            "byte-identical to the volume oracle")
                    if vfinal.get("router", {}).get(
                            "resume_count", 0) > 0:
                        resumes += 1
                elif not vfinal.get("retryable"):
                    failures.append(
                        f"cycle {cycle}: volume converge ended "
                        f"non-rejected: {vfinal.get('rejected')!r}")
    dup = {r: n for r, n in finals_per_rid.items() if n != 1}
    if dup:
        failures.append(f"exactly-once final rows violated: {dup}")
    if args.chaos >= 1 and resumes < 1:
        failures.append("no mid-stream resume observed across the run")
    snap = router.snapshot()
    router.close()
    summary = {
        "summary": "chaos", "cycles": args.chaos, "seed": args.seed,
        "volume": bool(args.volume),
        "specs": specs,
        "resumes_observed": resumes,
        "router_resumes": snap["router"]["resumes"],
        "mid_stream_failovers": snap["router"]["mid_stream_failovers"],
        "corrupt_responses": sum(p["corrupt_responses"]
                                 for p in snap["replicas"].values()),
        "chaos_injected": {site: sum(r.injected.get(site, 0)
                                     for r in reps)
                           for site in ("transport_send",
                                        "transport_recv",
                                        "transport_stream",
                                        "readyz_probe")},
        "wall_s": round(time.time() - t0, 1),
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


def run_chaos_matrix(args) -> int:
    """Storage-chaos soak (round 24): N cycles of the full
    ``scripts/chaos_matrix.py`` matrix — every disk fault mode crossed
    with every workload shape — each cycle under a DIFFERENT seed, so
    the hit-indexed schedules land the faults at different appends,
    spills, and stream rows every time.  Gates are the matrix's own
    standing invariants; any cycle reporting failures fails the soak."""
    import chaos_matrix

    failures: list[str] = []
    cycles = []
    t0 = time.time()
    for cycle in range(args.chaos_matrix):
        row = chaos_matrix.run_matrix(
            seed=args.seed + cycle,
            log=lambda m: None)   # per-cell chatter off; summary below
        cycles.append({"seed": row["seed"],
                       "cells_failed": row["cells_failed"],
                       "failures": row["failures"],
                       "wall_s": row["wall_s"]})
        if row["failures"]:
            failures.append(
                f"cycle {cycle} (seed {row['seed']}): "
                f"{row['failures']} failures, e.g. "
                f"{row['failure_detail'][:2]}")
        print(json.dumps({"cycle": cycle, "seed": row["seed"],
                          "cells": row["cells_total"],
                          "failures": row["failures"]}), flush=True)
    summary = {
        "summary": "chaos-matrix", "cycles": args.chaos_matrix,
        "seed": args.seed, "per_cycle": cycles,
        "wall_s": round(time.time() - t0, 1),
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


def run_router_restart(args) -> int:
    """Crash-safe control-plane drill (round 19): N router lives over
    one WAL lineage; see module docstring for the gates."""
    import base64

    import numpy as np

    from _chaos_common import (
        converge_body, oracle_converge_final, request_with_backoff,
    )
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.serving.chaos import router_kill_due
    from parallel_convolution_tpu.serving.pricing import WorkPricer
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter, TenantQuotas,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    rng = random.Random(args.seed)
    img = imageio.generate_test_image(40, 56, "grey", seed=args.seed)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    want1 = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)

    def factory():
        return ConvolutionService(mesh_from_spec("1x2"),
                                  max_delay_s=0.002, max_queue=256)

    def cbody(rid: str) -> dict:
        return converge_body(b64, 40, 56, rid, tenant="drill")

    try:
        oracle_final = oracle_converge_final(factory, cbody("oracle"))
    except RuntimeError as e:
        print(json.dumps({"summary": "router-restart", "failures": 1,
                          "detail": str(e)}))
        return 1

    reps = [InProcessReplica(factory, name=f"rr{i}") for i in range(3)]
    state_dir = Path(args.state_dir or tempfile.mkdtemp(
        prefix="pctpu-router-restart-"))
    wal_path = state_dir / "router.wal"

    def mk_router():
        return ReplicaRouter(
            reps, wal=str(wal_path),
            quotas=TenantQuotas(rate=1.0, burst=1e6),
            pricer=WorkPricer(min_units=1e-9),
            breaker_threshold=3, breaker_cooldown_s=0.2,
            poll_interval_s=0.05, start_health=False)

    failures: list[str] = []
    finals_per_rid: dict[str, int] = {}
    resumes = 0
    epochs: list[int] = []
    t0 = time.time()
    prev_router = None
    pending: str | None = None
    lives = args.router_restart + 1   # the extra life drains the tail
    for life in range(lives):
        router = mk_router()
        epochs.append(router.epoch)
        if len(epochs) >= 2 and epochs[-1] <= epochs[-2]:
            failures.append(
                f"life {life}: epoch {epochs[-1]} did not bump past "
                f"{epochs[-2]}")
        if prev_router is not None:
            # The dead life's object is now a zombie: fenced everywhere.
            _, wz = prev_router.request({
                "image_b64": b64, "rows": 40, "cols": 56,
                "mode": "grey", "filter": "blur3", "iters": 1,
                "request_id": f"z{life}", "tenant": "drill"})
            if wz.get("rejected") != "stale_epoch" or wz.get(
                    "retryable"):
                failures.append(
                    f"life {life}: zombie not fenced "
                    f"({wz.get('rejected')!r})")
            prev_router.close(close_replicas=False)
        if pending is not None:
            # Client retry of the crash-interrupted job: must RESUME
            # from the WAL-recovered token and finish byte-identical.
            st, rows = router.converge(cbody(pending))
            drained = list(rows) if st == 200 else []
            for r in drained:
                if r.get("kind") == "final":
                    finals_per_rid[pending] = finals_per_rid.get(
                        pending, 0) + 1
            final = drained[-1] if drained else {}
            if final.get("kind") != "final":
                failures.append(
                    f"life {life}: retry of {pending!r} did not finish")
            else:
                if final.get("router", {}).get("resume_count", 0) >= 1:
                    resumes += 1
                else:
                    failures.append(
                        f"life {life}: {pending!r} restarted instead "
                        f"of resuming ({final.get('router')})")
                if final.get("image_b64") != oracle_final["image_b64"]:
                    failures.append(
                        f"life {life}: resumed final not "
                        "byte-identical to oracle")
            pending = None
        # Batch sanity through this life (epoch stamps observed).
        wire = request_with_backoff(router, {
            "image_b64": b64, "rows": 40, "cols": 56, "mode": "grey",
            "filter": "blur3", "iters": 1,
            "request_id": f"b{life}", "tenant": "drill"})
        if wire.get("ok"):
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(40, 56)
            if not np.array_equal(got, want1):
                failures.append(f"life {life}: batch byte mismatch")
            if wire.get("router", {}).get("epoch") != router.epoch:
                failures.append(f"life {life}: missing epoch stamp")
        elif not wire.get("retryable"):
            failures.append(
                f"life {life}: non-rejected batch failure "
                f"{wire.get('rejected')}")
        if life == lives - 1:
            router.close(close_replicas=False)
            break
        # Start a job and CRASH this router mid-stream at a seeded row.
        rid = f"rr-job{life}"
        kill_at = rng.randint(1, 3)
        with faults.injected(f"router_kill:{kill_at}",
                             seed=args.seed + life):
            st, rows = router.converge(cbody(rid))
            if st != 200:
                failures.append(
                    f"life {life}: job admission failed ({st})")
            else:
                killed = False
                for row in rows:
                    if row.get("kind") == "final":
                        finals_per_rid[rid] = finals_per_rid.get(
                            rid, 0) + 1
                    if router_kill_due():
                        killed = True
                        break   # abandon un-closed: the crash
                if killed:
                    pending = rid
                else:
                    failures.append(
                        f"life {life}: router_kill never fired")
        prev_router = router

    dup = {r: n for r, n in finals_per_rid.items() if n != 1}
    if dup:
        failures.append(f"exactly-once final rows violated: {dup}")
    if args.router_restart >= 1 and resumes < 1:
        failures.append("no cross-restart resume observed")
    summary = {
        "summary": "router-restart", "lives": lives, "seed": args.seed,
        "epochs": epochs,
        "resumes_observed": resumes,
        "finals_per_request": finals_per_rid,
        "wal": str(wal_path),
        "wall_s": round(time.time() - t0, 1),
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


def run_shard_kill(args) -> int:
    """Sharded control-plane drill (round 21): N kill-one-of-three
    cycles over PERSISTENT per-shard WAL lineages.

    Each cycle boots a fresh 3-router fleet over the SAME three shard
    lineages (a boot over an existing lineage is itself the r19 fenced
    takeover, so epochs ratchet monotonically across cycles), rotates
    which router owns which shard, then:

    1. closed-loop traffic hammers the two NON-victim shards
       throughout, with per-phase latency capture;
    2. a converge stream on the victim's shard is cut mid-flight by
       ``hard_stop`` (the in-process SIGKILL: flocks released, nothing
       fenced gracefully);
    3. surviving peers detect the death via anti-entropy misses and
       the deterministic successor performs the cross-shard fenced
       takeover of the orphaned lineage;
    4. the shard client refreshes the map and retries: the job RESUMES
       byte-identical to the uninterrupted oracle, exactly one final
       per request_id; the zombie owner is rejected typed
       ``stale_epoch``.

    Gates: zero non-rejected failures on surviving shards in EVERY
    phase, every cycle's resumed final byte-identical, exactly-once
    finals, one takeover per cycle, and the surviving shards' p99
    during the takeover window flat against the pre-kill baseline
    (<= 5x + 25 ms slack — in-process noise, not a perf claim).
    """
    import base64
    import threading

    import numpy as np

    from _chaos_common import oracle_converge_final
    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.peers import (
        InProcessPeer, ShardClient, ShardRouter, shard_of,
    )
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, route_key,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    img = imageio.generate_test_image(32, 48, "grey", seed=args.seed)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()

    def factory():
        return ConvolutionService(mesh_from_spec("1x2"),
                                  max_delay_s=0.002, max_queue=256)

    def batch_body(iters: int, rid: str) -> dict:
        return {"image_b64": b64, "rows": 32, "cols": 48,
                "mode": "grey", "filter": "blur3", "iters": iters,
                "request_id": rid}

    def cv_body(rid: str) -> dict:
        return {"image_b64": b64, "rows": 32, "cols": 48,
                "mode": "grey", "filter": "jacobi3",
                "backend": "shifted", "quantize": False, "tol": 0.0,
                "max_iters": 40, "check_every": 10, "request_id": rid}

    # iters is a route-key field: scan it until every shard has a
    # batch config (the traffic spreader) — plus the converge shard.
    by_shard: dict[str, int] = {}
    for it in range(1, 120):
        s = shard_of(route_key(batch_body(it, "probe")), 3)
        by_shard.setdefault(s, it)
        if len(by_shard) == 3:
            break
    oracles = {it: oracle.run_serial_u8(
        img, filters.get_filter("blur3"), it)
        for it in by_shard.values()}
    kill_shard = shard_of(route_key(cv_body("probe")), 3)
    other_shards = [s for s in ("0", "1", "2") if s != kill_shard]
    oracle_final = oracle_converge_final(factory, cv_body("oracle"))

    names = ["sA", "sB", "sC"]
    reps = [InProcessReplica(factory, name=f"sk{i}") for i in range(3)]
    state_dir = Path(args.state_dir or tempfile.mkdtemp(
        prefix="pctpu-shard-kill-"))

    failures: list[str] = []
    finals_per_rid: dict[str, int] = {}
    takeovers = 0
    p99s: list[dict] = []
    t0 = time.time()

    for cycle in range(args.shard_kill):
        # Rotate ownership so the victim differs per cycle (the victim
        # is whoever owns the converge body's shard this cycle).
        rot = names[cycle % 3:] + names[:cycle % 3]
        assign = {str(i): rot[i] for i in range(3)}
        routers = {}
        for nm in names:
            routers[nm] = ShardRouter(
                nm, reps, n_shards=3,
                owned=[s for s, o in assign.items() if o == nm],
                state_dir=state_dir, assignments=assign,
                start_sync=False, start_health=False,
                breaker_cooldown_s=0.2, wal_fsync=False)
        for nm in names:
            routers[nm].peers = [InProcessPeer(routers[o])
                                 for o in names if o != nm]
        victim = routers[assign[kill_shard]]
        survivors = [routers[nm] for nm in names
                     if nm != assign[kill_shard]]

        phase = {"now": "before"}
        lat: dict[str, list[float]] = {"before": [], "during": [],
                                       "after": []}
        lat_lock = threading.Lock()
        stop = threading.Event()

        def pound(shard: str, widx: int, routers=routers, phase=phase,
                  lat=lat, lat_lock=lat_lock, stop=stop, cycle=cycle):
            cl = ShardClient(list(routers.values()))
            it = by_shard[shard]
            j = 0
            while not stop.is_set():
                j += 1
                t1 = time.perf_counter()
                _, w = cl.request(
                    batch_body(it, f"c{cycle}t{widx}-{j}"))
                dt = (time.perf_counter() - t1) * 1000.0
                if w.get("ok"):
                    got = np.frombuffer(
                        base64.b64decode(w["image_b64"]),
                        np.uint8).reshape(32, 48)
                    with lat_lock:
                        lat[phase["now"]].append(dt)
                        if not np.array_equal(got, oracles[it]):
                            failures.append(
                                f"cycle {cycle}: surviving-shard "
                                f"byte mismatch on shard {shard}")
                elif not w.get("retryable"):
                    with lat_lock:
                        failures.append(
                            f"cycle {cycle}: non-rejected failure on "
                            f"surviving shard {shard}: "
                            f"{w.get('rejected')!r}")
                else:
                    time.sleep(0.01)

        threads = [threading.Thread(target=pound, args=(s, i))
                   for i, s in enumerate(other_shards)]
        for th in threads:
            th.start()
        time.sleep(0.5)   # pre-kill baseline window (warm + measured)

        client = ShardClient(list(routers.values()))
        rid = f"sk-job{cycle}"
        st, rows = client.converge(cv_body(rid))
        pre = []
        if st != 200:
            failures.append(f"cycle {cycle}: admission failed ({st})")
        else:
            for row in rows:
                pre.append(row)
                if row.get("kind") == "final":
                    finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
                if len(pre) >= 2:
                    break   # abandon un-closed: the crash
        phase["now"] = "during"
        victim.hard_stop()
        # Survivors detect the death and take over deterministically.
        deadline = time.time() + 30.0
        owner = None
        while time.time() < deadline and owner is None:
            for r in survivors:
                r.sync_now()
            owner = next((r for r in survivors
                          if kill_shard in r._sub), None)
        if owner is None:
            failures.append(f"cycle {cycle}: no takeover within 30s")
        else:
            takeovers += 1
        # In-process takeover completes in single-digit ms — hold the
        # measurement window open so the p99 gate has samples that
        # actually bracket it.
        time.sleep(0.4)
        phase["now"] = "after"
        # Client retry: refresh the map, resume, finish byte-identical.
        client.refresh()
        st, rows = client.converge(cv_body(rid))
        drained = list(rows) if st == 200 else []
        for r in drained:
            if r.get("kind") == "final":
                finals_per_rid[rid] = finals_per_rid.get(rid, 0) + 1
        final = drained[-1] if drained else {}
        if final.get("kind") != "final":
            failures.append(f"cycle {cycle}: retry did not finish")
        else:
            if final.get("router", {}).get("resume_count", 0) < 1:
                failures.append(
                    f"cycle {cycle}: restarted instead of resuming "
                    f"({final.get('router')})")
            if pre and final.get("iters", 0) <= pre[-1].get("iters", 0):
                failures.append(
                    f"cycle {cycle}: final iters {final.get('iters')} "
                    f"not past pre-kill {pre[-1].get('iters')}")
            if final.get("image_b64") != oracle_final["image_b64"]:
                failures.append(
                    f"cycle {cycle}: resumed final not byte-identical")
        # Zombie: the dead owner's sub-router is fenced typed.
        _, zrows = victim.sub(kill_shard).converge(
            cv_body(f"z{cycle}"))
        zfirst = next(iter(zrows), {})
        if zfirst.get("rejected") != "stale_epoch":
            failures.append(
                f"cycle {cycle}: zombie not fenced "
                f"({zfirst.get('rejected')!r})")
        time.sleep(0.3)   # post-takeover window
        stop.set()
        for th in threads:
            th.join(10.0)
        p_before = _pct_ms(lat["before"])
        p_during = _pct_ms(lat["during"])
        p99s.append({"cycle": cycle, "victim": victim.name,
                     "p99_before_ms": p_before,
                     "p99_during_ms": p_during,
                     "n_before": len(lat["before"]),
                     "n_during": len(lat["during"])})
        if not lat["during"]:
            failures.append(
                f"cycle {cycle}: surviving shards served NOTHING "
                "during the takeover window")
        elif (p_before is not None and p_during is not None
                and p_during > 5.0 * p_before + 25.0):
            failures.append(
                f"cycle {cycle}: surviving-shard p99 spiked during "
                f"takeover: {p_during:.1f}ms vs baseline "
                f"{p_before:.1f}ms")
        for r in routers.values():
            try:
                r.close(close_replicas=False)
            except Exception:  # noqa: BLE001 — victim already dead
                pass

    for rep in reps:
        rep.close()
    dup = {r: n for r, n in finals_per_rid.items() if n != 1}
    if dup:
        failures.append(f"exactly-once final rows violated: {dup}")
    summary = {
        "summary": "shard-kill", "cycles": args.shard_kill,
        "seed": args.seed,
        "kill_shard": kill_shard,
        "takeovers": takeovers,
        "finals_per_request": finals_per_rid,
        "p99_by_cycle": p99s,
        "state_dir": str(state_dir),
        "wall_s": round(time.time() - t0, 1),
        "failures": len(failures),
        "failure_detail": failures[:8],
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


def _pct_ms(vals, q: float = 0.99):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(round(q * (len(vs) - 1))))]


def run_autoscale_drill(args) -> int:
    """Sustained-load autoscale drill: N grow/shrink cycles (round 17).

    One in-process replica behind the router with the control loop
    armed; each cycle saturates the pool with a closed-loop worker pack
    until the autoscaler GROWS it, then idles until it SHRINKS back —
    the serving analogue of the reshape ladder drill, run repeatedly so
    flapping, leaked replicas, and drain races surface.  Gates:

    1. zero non-rejected failures across every cycle (typed retryable
       sheds re-driven with capped backoff, the loadgen contract);
    2. every completed response byte-identical to the NumPy oracle;
    3. every cycle both grew (>= 1 added replica) and shrank (back to
       the 1-replica floor);
    4. at least one scale-up pre-warmed its ring shard (warm placement
       exercised, not just pool arithmetic).
    """
    import base64
    import threading

    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel.mesh import mesh_from_spec
    from parallel_convolution_tpu.serving.autoscaler import AutoScaler
    from parallel_convolution_tpu.serving.router import (
        InProcessReplica, ReplicaRouter,
    )
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    n_cycles = args.autoscale
    img = imageio.generate_test_image(40, 56, "grey", seed=args.seed)
    b64 = base64.b64encode(np.ascontiguousarray(img).tobytes()).decode()
    iters_pool = [1, 2, 3]
    oracles = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"),
                                        it) for it in iters_pool}

    def factory():
        return ConvolutionService(mesh_from_spec("1x2"), max_batch=1,
                                  max_delay_s=0.001, max_queue=16)

    def transport_factory(name):
        return InProcessReplica(factory, name=name)

    router = ReplicaRouter([InProcessReplica(factory, name="r0")],
                           poll_interval_s=0.05, breaker_cooldown_s=0.2)
    scaler = AutoScaler(router, transport_factory, min_replicas=1,
                        max_replicas=2, up_pressure=0.3,
                        down_pressure=0.02, up_ticks=2, down_ticks=10,
                        cooldown_s=1.0, interval_s=0.2, drain_s=5.0)
    results, lock = [], threading.Lock()
    counter = [0]

    def one(i: int) -> None:
        it = iters_pool[i % len(iters_pool)]
        body = {"image_b64": b64, "rows": 40, "cols": 56, "mode": "grey",
                "filter": "blur3", "iters": it, "request_id": f"as{i}"}
        for attempt in range(6):
            status, wire = router.request(dict(body), tenant="drill")
            if wire.get("ok") or not wire.get("retryable"):
                break
            time.sleep(min(float(wire.get("retry_after_s") or 0.05), 0.5))
        ok = bool(wire.get("ok"))
        byte_ok = None
        if ok:
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(40, 56)
            byte_ok = bool(np.array_equal(got, oracles[it]))
        with lock:
            results.append({"i": i, "ok": ok, "byte_ok": byte_ok,
                            "rejected": wire.get("rejected"),
                            "retryable": wire.get("retryable")})

    # Observatory warm-up: the pre-warm worklist needs observed configs.
    for i in range(len(iters_pool)):
        one(i)
    scaler.start()
    cycles = []
    for cycle in range(n_cycles):
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                with lock:
                    i = counter[0] + 100
                    counter[0] += 1
                one(i)

        pack = [threading.Thread(target=worker, daemon=True)
                for _ in range(24)]
        for th in pack:
            th.start()
        grew = False
        t_sat = time.time()
        while time.time() - t_sat < 30.0:
            if len(router.ring.members()) >= 2:
                grew = True
                break
            time.sleep(0.1)
        stop.set()
        for th in pack:
            th.join(60)
        shrank = False
        t_idle = time.time()
        while time.time() - t_idle < 30.0:
            if len(router.ring.members()) == 1:
                shrank = True
                break
            time.sleep(0.1)
        cycles.append({"cycle": cycle, "grew": grew, "shrank": shrank})
    scaler.close()
    router.close()

    completed = [r for r in results if r["ok"]]
    byte_fails = [r for r in completed if not r["byte_ok"]]
    non_rejected = [r for r in results
                    if not r["ok"] and not r.get("retryable")]
    bad_cycles = [c for c in cycles if not (c["grew"] and c["shrank"])]
    prewarmed = scaler.stats["prewarmed_configs"]
    failures = (len(byte_fails) + len(non_rejected) + len(bad_cycles)
                + (1 if prewarmed < 1 else 0))
    summary = {
        "summary": "autoscale-drill", "cycles": cycles,
        "n": len(results), "completed": len(completed),
        "scaler": dict(scaler.stats),
        "prewarmed_configs": prewarmed,
        "byte_mismatches": len(byte_fails),
        "non_rejected_failures": len(non_rejected),
        "failures": failures,
    }
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


RESHAPE_TARGETS = [(1, 2), (2, 2), (1, 1)]


def run_reshape_trial(spec: str, seed: int, out_path: str) -> int:
    """One elastic-recovery drill: crash on 2x4, resume on every shrink.

    Phase 1 installs ``spec`` (a random checkpoint/compile/exchange
    fault) and runs a checkpointed job on the 2x4 CPU mesh until the
    injected crash.  Phase 2 copies the post-crash checkpoint dir once
    per target grid (1x2 / 2x2 / 1x1) and resumes each INDEPENDENTLY
    from whatever — possibly torn — state the crash left: the
    grid-agnostic reshard + quarantine walk must land every one
    byte-identical to the single-device oracle.  Exit 0 iff all do.
    """
    import shutil
    import warnings

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.utils import checkpoint, imageio

    rng = random.Random(seed)
    filt = filters.get_filter(rng.choice(["blur3", "gaussian5", "sharpen3"]))
    H, W = rng.randrange(33, 70), rng.randrange(33, 70)
    total, every = rng.randrange(6, 11), rng.randrange(2, 4)
    fuse = rng.choice([1, 2, 2])  # biased fused: mid-fuse resumes matter
    mesh8 = mesh_lib.make_grid_mesh(jax.devices()[:8], (2, 4))
    img = imageio.generate_test_image(H, W, "grey", seed=seed)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    ck = tempfile.mkdtemp(prefix="pctpu_reshape_trial_")

    crashed = None
    with faults.injected(spec, seed=seed) as plan:
        try:
            xs, valid_hw, _ = step._prepare(x, mesh8, filt.radius)
            checkpoint.run_checkpointed(xs, filt, total, mesh8, valid_hw,
                                        ckpt_dir=ck, every=every, fuse=fuse)
        except Exception as e:  # noqa: BLE001 — the injected crash
            crashed = repr(e)
        fired = plan.fired
    want = oracle.run_serial_u8(img, filt, total)
    targets, ok = {}, True
    for shape in RESHAPE_TARGETS:
        name = "x".join(map(str, shape))
        tdir = f"{ck}_resume_{name}"
        shutil.copytree(ck, tdir, dirs_exist_ok=True)
        tmesh = mesh_lib.make_grid_mesh(
            jax.devices()[: shape[0] * shape[1]], shape)
        xs2, valid_hw, _ = step._prepare(x, tmesh, filt.radius)
        try:
            meta = checkpoint.load_meta(tdir)
            resumed_from = None if meta is None else int(meta["iters_done"])
        except checkpoint.CheckpointCorrupt:
            resumed_from = "torn"
        with warnings.catch_warnings():
            # Reshard notes + quarantine warnings are this drill's
            # expected operation, not anomalies to surface per trial.
            warnings.simplefilter("ignore", checkpoint.CheckpointWarning)
            out = checkpoint.run_checkpointed(
                xs2, filt, total, tmesh, valid_hw, ckpt_dir=tdir,
                every=every, fuse=fuse)
        got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]]
        t_ok = bool(np.array_equal(got[0].astype(np.uint8), want))
        targets[name] = {"ok": t_ok, "resumed_from": resumed_from}
        ok &= t_ok
    row = {
        "ok": ok, "mode": "reshape", "spec": spec, "seed": seed,
        "crashed": crashed, "fired": [list(f) for f in fired],
        "filter": filt.name, "H": H, "W": W, "total": total,
        "every": every, "fuse": fuse, "source_mesh": "2x4",
        "targets": targets,
    }
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    Path(out_path).write_text(json.dumps(row))
    print(json.dumps(row), flush=True)
    return 0 if ok else 1


def _sample_serve_fault_spec(rng: random.Random) -> str:
    """A random transient compile/exchange plan for the serving drill.

    Hit-indexed only (no open ranges, no probabilities): bounded retry
    must heal every sampled plan DETERMINISTICALLY — a plan that fails
    all compiles forever would test retry exhaustion, which has its own
    unit test, not the soak's heal-and-serve property.
    """
    site = rng.choice(["backend_compile", "backend_compile",
                       "halo_exchange"])
    return f"{site}:{rng.randrange(1, 4)}"


def _sample_reshape_fault_spec(rng: random.Random, n_shards: int) -> str:
    """A crash that lands AFTER the first save completes.

    The reshape drill's point is resuming a REAL snapshot on a different
    grid, so every sampled (site, hit) leaves snapshot 1 intact: shard
    hits span the second save (tearing it additionally exercises the
    quarantine walk mid-reshard), meta hits 3/4 are the second save's
    meta/LATEST writes, and exchange hit 2 is a later chunk's compile.
    With short runs some hits never fire — the run then completes clean
    and the resume still reshards from its snapshots.
    """
    site = rng.choice(["checkpoint_write_shard"] * 3
                      + ["checkpoint_write_meta"] * 2 + ["halo_exchange"])
    if site == "checkpoint_write_shard":
        hit = rng.randrange(n_shards + 1, 2 * n_shards + 1)
    elif site == "checkpoint_write_meta":
        hit = rng.randrange(3, 5)
    else:
        hit = 2
    return f"{site}:{hit}"


def _sample_fault_spec(rng: random.Random, n_shards: int) -> str:
    """A random single-site plan biased toward checkpoint tears."""
    site = rng.choice(
        ["checkpoint_write_shard"] * 3 + ["checkpoint_write_meta"] * 2
        + ["backend_compile", "halo_exchange"])
    if site == "checkpoint_write_shard":
        hit = rng.randrange(1, 2 * n_shards + 1)  # spans two save rounds
    elif site == "checkpoint_write_meta":
        hit = rng.randrange(1, 5)  # meta + LATEST consults, two saves
    else:
        hit = 1
    return f"{site}:{hit}"


def run_fault_soak(args) -> int:
    """Sample ``--faults`` random plans; run each drill as a supervised leg."""
    from parallel_convolution_tpu.resilience.retry import RetryPolicy
    from parallel_convolution_tpu.resilience.supervisor import (
        Leg, Supervisor,
    )
    from parallel_convolution_tpu.utils.platform import child_env_cpu

    rng = random.Random(args.seed)
    state = Path(args.state_dir or tempfile.mkdtemp(prefix="pctpu_fault_soak_"))
    n_trials = args.reshape or args.faults
    legs = []
    for i in range(n_trials):
        if args.reshape:
            # Post-first-save crash sites, resumed across grids.
            spec = _sample_reshape_fault_spec(rng, n_shards=8)
            trial_flag = "--reshape-trial"
        elif args.serve:
            spec = _sample_serve_fault_spec(rng)
            trial_flag = "--serve-trial"
        else:
            spec = _sample_fault_spec(rng, n_shards=8)
            trial_flag = "--fault-trial"
        out = state / f"trial_{i:03d}.json"
        legs.append(Leg(
            name=f"trial_{i:03d}",
            cmd=[sys.executable, os.path.abspath(__file__),
                 trial_flag, spec,
                 "--trial-seed", str(rng.randrange(10_000)),
                 "--trial-out", str(out)],
            done_file=str(out), done_pattern='"ok": true',
            timeout=600.0, env=child_env_cpu(8),
        ))
    t0 = time.time()
    sup = Supervisor(legs, state,
                     policy=RetryPolicy(max_attempts=2, base_delay=0.2,
                                        max_delay=1.0, seed=args.seed))
    rc = sup.run()
    fails = 0
    for leg in legs:
        p = Path(leg.done_file)
        if p.exists():
            print(p.read_text().strip(), flush=True)
        if not leg.is_complete():
            fails += 1
    summary = {
        "summary": "reshape-soak" if args.reshape else "fault-soak",
        "mode": ("reshape" if args.reshape
                 else "serve" if args.serve else "batch"),
        "n": n_trials, "seed": args.seed,
        "failures": fails, "state_dir": str(state), "supervisor_rc": rc,
        "wall_s": round(time.time() - t0, 1),
    }
    if args.reshape:
        summary["targets"] = ["x".join(map(str, s))
                              for s in RESHAPE_TARGETS]
    if args.summary_out:
        p = Path(args.summary_out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(summary) + "\n")
    print(json.dumps(summary), flush=True)
    return 1 if (fails or rc) else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--converge", action="store_true",
                    help="soak the run-to-convergence path (C6) instead "
                         "of fixed-count iteration")
    ap.add_argument("--faults", type=int, default=0, metavar="N",
                    help="resilience mode: run N random injected-fault "
                         "crash/resume drills through the supervised "
                         "runner instead of the byte-compare soak")
    ap.add_argument("--serve", action="store_true",
                    help="with --faults: run the drills through the "
                         "serving engine (retry/degradation must heal "
                         "injected compile/exchange faults into "
                         "byte-identical responses; then a clean restart "
                         "must serve the requested tier)")
    ap.add_argument("--reshape", type=int, default=0, metavar="N",
                    help="elastic-recovery mode: run N crash-on-2x4 / "
                         "resume-on-1x2,2x2,1x1 reshard drills through "
                         "the supervised runner; every resumed output "
                         "must byte-match the single-device oracle")
    ap.add_argument("--router-kill", type=int, default=0, metavar="N",
                    help="replica-router drill: 3 in-process replicas "
                         "behind the router, N kill/revive cycles under "
                         "continuous traffic; gates on zero non-rejected "
                         "failures, byte-identical results, and >= 1 "
                         "observed failover")
    ap.add_argument("--autoscale", type=int, default=0, metavar="N",
                    help="fleet-autoscale drill: 1 replica + the control "
                         "loop, N saturate/idle cycles; gates on zero "
                         "non-rejected failures, byte-identical results, "
                         "every cycle growing AND shrinking the pool, "
                         "and >= 1 pre-warmed ring shard")
    ap.add_argument("--chaos", type=int, default=0, metavar="N",
                    help="chaos-transport drill: 3 chaos-wrapped "
                         "replicas behind the durable router, N cycles "
                         "of sampled seeded transport-fault schedules "
                         "over mixed batch/converge traffic with "
                         "mid-stream kills; gates on zero non-rejected "
                         "failures, byte-identical completions incl. "
                         "resumed converge finals, >= 1 mid-stream "
                         "resume, exactly one final row per request_id")
    ap.add_argument("--volume", action="store_true",
                    help="with --chaos: every cycle also streams a "
                         "rank-3 (D,H,W) volume converge job, killed "
                         "mid-flight on odd cycles — resumed finals "
                         "must stay byte-identical to the volume "
                         "oracle")
    ap.add_argument("--chaos-matrix", type=int, default=0, metavar="N",
                    help="storage-chaos soak: N cycles of the full "
                         "scripts/chaos_matrix.py fault-mode x "
                         "workload matrix, each under a different "
                         "seed; gates on every cycle reporting zero "
                         "failures (standing invariants: typed-only "
                         "failures, byte-identical completions, "
                         "exactly-once finals, no stale-byte serves)")
    ap.add_argument("--router-restart", type=int, default=0, metavar="N",
                    help="crash-safe control-plane drill: N router "
                         "lives over one WAL lineage; each life "
                         "resumes the previous life's crash-"
                         "interrupted converge job from the recovered "
                         "token (byte-identical, exactly-once finals), "
                         "crashes mid-stream at a seeded router_kill "
                         "row, and proves the dead life is fenced "
                         "typed stale_epoch")
    ap.add_argument("--shard-kill", type=int, default=0, metavar="N",
                    help="sharded control-plane drill: 3 active "
                         "routers over 3 per-shard WAL lineages, N "
                         "kill-one cycles under continuous traffic on "
                         "the surviving shards; gates on zero non-"
                         "rejected failures, byte-identical resumed "
                         "finals, exactly-once finals, one fenced "
                         "takeover per cycle, and the surviving "
                         "shards' p99 flat through the takeover")
    ap.add_argument("--summary-out", default=None, metavar="FILE",
                    help="also write the final summary row to FILE "
                         "(the tier-1 --elastic-smoke leg's done_file)")
    ap.add_argument("--state-dir", default=None,
                    help="--faults: supervisor state dir (default: mkdtemp)")
    # Hidden: one drill in a child process (the supervisor's leg cmd).
    ap.add_argument("--fault-trial", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--serve-trial", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--reshape-trial", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--trial-seed", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--trial-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    from parallel_convolution_tpu.obs import events as obs_events

    obs_events.install_from_env()  # PCTPU_OBS_EVENTS: drill timeline

    if args.fault_trial:
        return run_fault_trial(args.fault_trial, args.trial_seed,
                               args.trial_out)
    if args.serve_trial:
        return run_serve_trial(args.serve_trial, args.trial_seed,
                               args.trial_out)
    if args.reshape_trial:
        return run_reshape_trial(args.reshape_trial, args.trial_seed,
                                 args.trial_out)
    if args.serve and not args.faults:
        ap.error("--serve requires --faults N")
    if args.reshape and args.faults:
        ap.error("--reshape and --faults are separate modes")
    if args.router_kill:
        return run_router_kill(args)
    if args.router_restart:
        return run_router_restart(args)
    if args.shard_kill:
        return run_shard_kill(args)
    if args.autoscale:
        return run_autoscale_drill(args)
    if args.chaos:
        return run_chaos_drill(args)
    if args.chaos_matrix:
        return run_chaos_matrix(args)
    if args.faults or args.reshape:
        return run_fault_soak(args)

    import jax
    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.parallel import step
    from parallel_convolution_tpu.utils import imageio
    from parallel_convolution_tpu.utils.platform import enable_compile_cache

    # On the real chip the wall is dominated by remote Mosaic compiles
    # (one per sampled config); the persistent cache lets a timed-out
    # campaign's retry resume instead of recompiling the same seed's
    # configs from scratch.  No-op on the CPU mesh.
    enable_compile_cache()

    rng = random.Random(args.seed)
    n_dev = len(jax.devices())
    fails = 0
    t0 = time.time()
    for i in range(args.n):
        cfg = sample_converge(rng) if args.converge else sample(rng)
        while cfg["mesh"][0] * cfg["mesh"][1] > n_dev:
            cfg["mesh"] = rng.choice(MESH_SHAPES)
        if cfg["boundary"] == "periodic":
            # Documented contract: the torus needs grid-divisible dims.
            gr, gc = cfg["mesh"]
            cfg["H"] -= cfg["H"] % gr
            cfg["W"] -= cfg["W"] % gc
        # Documented contract: the fused slab needs blocks >= r * fuse
        # (step.py's up-front ValueError); shrink fuse to fit the
        # sampled geometry instead of sampling a rejected config.
        r = filters.get_filter(cfg["filter"]).radius
        gr, gc = cfg["mesh"]
        while cfg["fuse"] > 1 and (
                -(-cfg["H"] // gr) < r * cfg["fuse"]
                or -(-cfg["W"] // gc) < r * cfg["fuse"]):
            cfg["fuse"] //= 2
        if cfg["fuse"] == 1 and "interior_split" in cfg:
            cfg["interior_split"] = False
        row = dict(cfg, i=i, mesh="x".join(map(str, cfg["mesh"])))
        try:
            if args.converge:
                row.update(run_converge(cfg, jax, np, filters, oracle,
                                        mesh_lib, step, imageio))
            else:
                filt = filters.get_filter(cfg["filter"])
                mode = "grey" if cfg["channels"] == 1 else "rgb"
                img = imageio.generate_test_image(cfg["H"], cfg["W"], mode,
                                                  seed=cfg["img_seed"])
                want = oracle.run_serial_u8(img, filt, cfg["iters"],
                                            boundary=cfg["boundary"])
                mesh = mesh_lib.make_grid_mesh(
                    jax.devices()[: cfg["mesh"][0] * cfg["mesh"][1]],
                    cfg["mesh"])
                x = imageio.interleaved_to_planar(img).astype(np.float32)
                out = step.sharded_iterate(
                    x, filt, cfg["iters"], mesh=mesh, quantize=True,
                    backend=cfg["backend"], storage=cfg["storage"],
                    fuse=cfg["fuse"], boundary=cfg["boundary"],
                    tile=cfg["tile"], interior_split=cfg["interior_split"])
                got = imageio.planar_to_interleaved(
                    np.asarray(out).astype(np.uint8))
                ok = bool(np.array_equal(got, want))
                row["ok"] = ok
                if not ok:
                    diff = got.astype(int) - want.astype(int)
                    row["max_abs_diff"] = int(np.abs(diff).max())
                    row["n_diff"] = int((diff != 0).sum())
        except Exception as e:
            msg = repr(e)
            row["ok"] = False
            row["error"] = msg[:500]
        if not row["ok"]:
            fails += 1
        print(json.dumps(row), flush=True)
    print(json.dumps({
        "summary": "soak", "n": args.n, "seed": args.seed,
        "failures": fails, "devices": n_dev,
        "wall_s": round(time.time() - t0, 1),
    }), flush=True)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
