#!/bin/sh
# Second-recovery chip session (round 4): the records still waiting on
# TPU silicon after the tunnel's SECOND mid-round death (BASELINE.md
# status note).  Ordered by value so another outage costs the least:
#
#   1. flagship tile/fuse re-tune with the round-4 convex-clamp elision
#      (the headline number; the elision measured +39% on pallas/f32/fuse1
#      before the tunnel died)
#   2. rdma_on_silicon — two-size tiled probe with full error capture
#      (re-record the remote-compile HTTP 500)
#   3. tiled_repro_probe — six-step construct ladder attributing that
#      crash to a specific Pallas construct
#   4. validate_walls — the rerun whose output was lost
#   5. bench.py sanity
#
set -x
cd "$(dirname "$0")/.."

# Dead-tunnel guard: a dead tunnel makes jax HANG on backend init, which
# would eat the whole session window; fail fast instead.
timeout 60 python -c "import jax; print(jax.devices())"   || { echo "tunnel dead; aborting chip session" >&2; exit 1; }

run_to() {
  out="$1"; shift
  if "$@" > "$out.tmp" 2> "/tmp/$(basename "$out").err"; then
    mv "$out.tmp" "$out" && echo "$out OK"
  else
    # Never leave a stale .tmp in evidence/ — it reads like a record.
    rm -f "$out.tmp"
    echo "$out FAILED (stderr: /tmp/$(basename "$out").err)" >&2
  fi
}

run_to evidence/tune_convex_r4.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage bf16 \
    --iters 100 --tiles 1024x512,1536x512,2048x512,1024x768 --fuses 24,32,40
run_to evidence/tune_convex_r4_u8.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage u8 \
    --iters 100 --tiles 1024x512,2048x512 --fuses 32,40
run_to evidence/tune_isplit_r4.jsonl \
  python scripts/tune_pallas.py --backend pallas_sep --storage bf16 \
    --iters 100 --tiles 1024x512,512x512 --fuses 32 --isplit
run_to evidence/rdma_silicon.json python scripts/rdma_on_silicon.py
run_to evidence/tiled_repro.jsonl python scripts/tiled_repro_probe.py
run_to evidence/validate_walls.json python scripts/validate_walls.py
python bench.py > /tmp/bench_r4b_sanity.json 2> /tmp/bench_r4b_sanity.err \
  && tail -c 400 /tmp/bench_r4b_sanity.json
