#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline (BASELINE.json): Gpixels/sec/chip, 3×3 blur (the reference's own
kernel), 100 iterations, uint8 store-back semantics — measured on whatever
accelerator is attached (the driver runs this on the real TPU chip).

``vs_baseline``: the reference's published MPI numbers were unreadable
(empty mount, BASELINE.md provenance note), so the ratio is against the
honestly-measured single-process CPU serial baseline (C++ serial binary if
built, else the NumPy oracle) on the reference's canonical 1920×2520 image —
i.e. "TPU chips vs the serial C-class baseline", the same speedup the
reference's README tables report for MPI ranks vs serial.
"""

from __future__ import annotations

import json
import sys


def main() -> int:
    from parallel_convolution_tpu.utils.platform import ensure_live_backend

    # Dead-tunnel guard: probe + env-pin application (or labeled CPU
    # fallback) in one shared shim — see utils/platform.py.
    platform_note = ensure_live_backend()
    if platform_note:
        print(f"# {platform_note}", file=sys.stderr)

    import jax

    from parallel_convolution_tpu.utils.platform import (
        enable_compile_cache, on_tpu,
    )

    enable_compile_cache()

    from parallel_convolution_tpu.ops.filters import get_filter
    from parallel_convolution_tpu.parallel.mesh import make_grid_mesh
    from parallel_convolution_tpu.utils import bench

    platform = jax.default_backend()
    n_dev = len(jax.devices())
    mesh = make_grid_mesh()
    filt = get_filter("blur3")

    # Silicon guard for the magic-number round default: the compiled
    # Mosaic kernels rely on Mosaic NOT algebraically folding
    # (acc + 1.5*2^23) - 1.5*2^23 (XLA:CPU folds it; the interpret-mode
    # tests therefore exercise the barrier form, never the bare form
    # silicon runs).  One tiny quantized kernel vs the NumPy oracle per
    # driver round: if a Mosaic/jax upgrade ever starts folding, the
    # rounding vanishes and this byte-compare catches it loudly before
    # a throughput row is published.
    magic_guard = "skipped-off-tpu"
    if on_tpu():
        import jax.numpy as jnp
        import numpy as np

        from parallel_convolution_tpu.ops import oracle, pallas_stencil
        from parallel_convolution_tpu.utils import imageio

        gimg = imageio.generate_test_image(128, 256, "grey", seed=5)
        gwant = oracle.run_serial_u8(gimg, filt, 2)
        gx = imageio.interleaved_to_planar(gimg).astype(np.float32)
        gout = gx
        for _ in range(2):
            gout = pallas_stencil.correlate_shifted_pallas(
                jnp.asarray(gout), filt, quantize=True)
        ggot = imageio.planar_to_interleaved(
            np.asarray(gout).astype(np.uint8))
        magic_guard = "ok" if np.array_equal(ggot, gwant) else "MISMATCH"
        if magic_guard != "ok":
            print("# MAGIC-ROUND GUARD FAILED: compiled kernel bytes "
                  "diverge from the oracle — Mosaic may have started "
                  "folding the two-add round; see _round_mode_for",
                  file=sys.stderr)
        elif pallas_stencil._MAGIC_GUARD.get("ok") is False:
            if pallas_stencil._MAGIC_GUARD.get("cause") == "mismatch":
                # The library-level probe caught the fold FIRST and
                # already flipped every compiled kernel to rint — so the
                # bytes above compare clean.  The fold event itself is
                # still the terminal condition this guard exists to
                # surface (a silent ~14% perf regression plus an
                # unverified-compiler state), so report it as MISMATCH
                # rather than letting the self-heal hide it.
                magic_guard = "MISMATCH"
                print("# MAGIC-ROUND GUARD: library probe detected the "
                      "fold and fell back to rint — published bytes are "
                      "correct, but the magic-round assumption is broken "
                      "on this jax/Mosaic; see _compiled_magic_ok",
                      file=sys.stderr)
            else:
                # The probe itself crashed (tunnel blip, OOM): kernels run
                # rint conservatively and bytes are verified correct above
                # — a RETRYABLE condition, distinct from a detected fold,
                # so it must not trip the terminal-MISMATCH automation.
                magic_guard = "library-probe-failed"
                print("# MAGIC-ROUND GUARD: library probe errored (not a "
                      "fold); kernels fell back to rint — transient, "
                      "retryable", file=sys.stderr)

    # Size the workload to the hardware: big enough to saturate a TPU chip
    # (detected via device_kind — experimental proxy platforms report a
    # non-'tpu' platform name), small enough that a CPU fallback finishes.
    if on_tpu():
        shape, iters, reps = (8192, 8192), 100, 3
    else:
        shape, iters, reps = (1024, 1024), 20, 2

    # xla_conv at 8192² OOMs on v5e (XLA's conv lowering materializes a
    # ~34 GB intermediate for 1-channel NCHW); bench it at 4096² — still
    # saturating — so the comparison row exists.
    configs = [
        ("shifted", "f32", 1, shape),
        ("xla_conv", "f32", 1, (min(shape[0], 4096), min(shape[1], 4096))),
        ("pallas", "f32", 1, shape),
        ("shifted", "bf16", 4, shape),
        ("pallas", "bf16", 8, shape),
        ("pallas_sep", "bf16", 16, shape),
        ("pallas_sep", "bf16", 32, shape),
        # u8 carries: the reference's own buffer dtype — quarter the HBM
        # traffic of f32; exact by construction in quantize mode.
        ("pallas_sep", "u8", 16, shape),
        ("pallas_sep", "u8", 32, shape),
        # Round-4 experiment: unmasked-interior launch split (bit-identical
        # by construction; a default only if this row beats the flagship).
        ("pallas_sep+isplit", "bf16", 32, shape),
        # RDMA tier at a tiled-kernel-sized block: degenerate (no remote
        # partner) on a 1x1 mesh, but every driver round re-proves the
        # kernel + barrier compile and run on real silicon.  fuse=4 adds
        # the in-kernel temporal fusion row (T*r-deep exchange + T levels
        # per launch) — the tier's reason-to-exist lever; the RDMA-vs-
        # ppermute small-block A/B lives in scripts/rdma_fuse_ab.py.
        ("pallas_rdma", "f32", 1,
         (min(shape[0], 2048), min(shape[1], 2048))),
        ("pallas_rdma", "f32", 4,
         (min(shape[0], 2048), min(shape[1], 2048))),
    ]
    candidates = {}
    for backend, storage, fuse, cshape in configs:
        name = f"{backend}/{storage}/fuse{fuse}"
        isplit = backend.endswith("+isplit")
        if isplit:
            # Round 5: the split dispatches per-device edge-class launches,
            # so the row is meaningful on ANY grid (1x1 included).
            backend = backend[: -len("+isplit")]
        if cshape != shape:
            # Off-default shape must be visible in the candidate name so
            # wall_s values across rows can't be misread as comparable.
            name += f"@{cshape[0]}"
        try:
            row = bench.bench_iterate(
                cshape, filt, iters, mesh=mesh, backend=backend,
                storage=storage, fuse=fuse, reps=reps,
                interior_split=isplit,
            )
            candidates[name] = row
            print(f"# {name}: {row}", file=sys.stderr)
        except Exception as e:  # keep the bench robust: one line, always
            print(f"# {name} failed: {e!r}", file=sys.stderr)
    if not candidates:
        print(json.dumps({"metric": "Gpixels/sec/chip (3x3 conv, 100 iters)",
                          "value": 0.0, "unit": "Gpixels/s/chip",
                          "vs_baseline": 0.0, "error": "all backends failed"}))
        return 1

    best_name, best = max(
        candidates.items(), key=lambda kv: kv[1]["gpixels_per_s_per_chip"]
    )

    proxy = bench.bench_oracle_proxy(iters=2)
    print(f"# serial proxy: {proxy}", file=sys.stderr)

    # Halo p50: on a multi-device mesh this is the real number; on the
    # 1×1 single-chip mesh bench_halo_p50 refuses (there is no collective
    # to time) and the honest record is null + a labeled CPU-mesh
    # functional proxy from a clean subprocess.
    halo_row = {}
    try:
        halo_row = bench.bench_halo_p50((512, 512), r=1, mesh=mesh)
        print(f"# halo: {halo_row}", file=sys.stderr)
    except Exception as e:
        print(f"# halo bench failed: {e!r}", file=sys.stderr)
    halo_proxy = {}
    if halo_row.get("mesh") == "1x1":
        # Only the single-chip case earns the proxy; a null from a REAL
        # multi-device mesh (noise floor, error) must stay an explained
        # null, not be papered over with a CPU number.
        from parallel_convolution_tpu.utils import halo_proxy as hp

        halo_proxy = hp.run_in_subprocess()
        print(f"# halo cpu-mesh proxy: {halo_proxy}", file=sys.stderr)

    value = best["gpixels_per_s_per_chip"]
    result = {
        "metric": "Gpixels/sec/chip (3x3 conv, 100 iters)",
        "value": value,
        "unit": "Gpixels/s/chip",
        "vs_baseline": round(value / proxy["gpixels_per_s"], 2),
        "platform": platform,
        # What the winning row ACTUALLY ran on (bench_iterate stamps every
        # row): the BENCH_r04/r05 failure mode was exactly this field
        # missing — a CPU fallback published as the chip headline.
        "effective_backend": best.get("effective_backend"),
        "row_platform": best.get("platform"),
        "devices": n_dev,
        "best_backend": best_name,
        "workload": best["workload"],
        "wall_s": best["wall_s"],
        "halo_p50_us": halo_row.get("p50_us"),
        "serial_proxy_gpixels_per_s": proxy["gpixels_per_s"],
        "serial_proxy_impl": proxy["impl"],
        # Denominator provenance: median-of-N with spread, so vs_baseline
        # swings can be attributed (the single-trial proxy moved ±20%
        # between identical-code rounds r01-r03).
        "serial_proxy_reps": proxy.get("reps"),
        "serial_proxy_spread_pct": proxy.get("spread_pct"),
        "magic_round_guard": magic_guard,
    }
    if halo_row.get("unmeasurable"):
        result["halo_p50_note"] = halo_row["unmeasurable"]
    for key in ("noise_floor", "clamped_trials"):
        if halo_row.get(key):
            result[f"halo_{key}"] = halo_row[key]
    if halo_proxy.get("p50_us") is not None:
        # Labeled functional proxy: same compiled ppermute exchange, 8
        # virtual CPU devices — mechanism + magnitude, not ICI latency.
        result["halo_p50_cpu_mesh_proxy_us"] = halo_proxy["p50_us"]
        result["halo_p50_proxy_mesh"] = halo_proxy.get("mesh")
    if platform_note:
        result["platform_note"] = platform_note
    # The r04/r05 lesson, now enforced: when the winning row did not run
    # on TPU silicon, the row is still printed — fully labeled — but the
    # run exits nonzero so automation can never book a CPU number as the
    # chip record.  (ensure_live_backend's tunnel fallback and a plain
    # CPU container both land here.)
    cpu_fallback = not on_tpu()
    if cpu_fallback:
        result["cpu_fallback"] = True
        print("# CPU FALLBACK: no TPU silicon behind this run — row is "
              "labeled and exit code is nonzero; this is NOT the chip "
              "record", file=sys.stderr)
    print(json.dumps(result))
    # A failed magic-round guard means the compiled kernels' bytes are
    # wrong — publish the labeled row (the guard field names the cause)
    # but exit nonzero so automation cannot treat the run as healthy.
    return 1 if (magic_guard == "MISMATCH" or cpu_fallback) else 0


if __name__ == "__main__":
    sys.exit(main())
