"""Test environment: hermetic multi-device CPU JAX.

The reference needed a real MPI cluster to exercise >1 rank; this framework's
tests instead force 8 virtual CPU devices (SURVEY.md §4), so halo exchange,
corner propagation, and convergence psum are all testable on any machine.
These env vars must be set before jax initializes a backend, hence here at
conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Force CPU even when the ambient env pins a TPU platform (JAX_PLATFORMS=axon
# here).  jax may already be imported by a site hook with the old env
# snapshot, so go through jax.config (valid until a backend initializes).
# Override with PCTPU_TEST_PLATFORM=tpu to run the suite on a real chip.
from parallel_convolution_tpu.utils.platform import force_platform

_want = os.environ.get("PCTPU_TEST_PLATFORM", "cpu")
force_platform(_want)

import jax

# Fail LOUDLY at collection if the pin didn't take (e.g. a site hook already
# initialized a backend): silently running the suite on the TPU proxy would
# break interpret-mode assumptions and burn real chip time.
_got = jax.devices()[0].platform
if (_want == "cpu") != (_got == "cpu"):
    # A deliberate tpu/axon override may report platform 'tpu' under a proxy
    # name, so exact equality can't be enforced — but cpu-wanted-got-else
    # and else-wanted-got-cpu are both always pin failures.
    raise RuntimeError(
        f"test platform pin failed: wanted {_want!r}, backend initialized "
        f"on {_got!r} (did something import/init jax before conftest?)"
    )

import numpy as np
import pytest

from parallel_convolution_tpu.utils import imageio


@pytest.fixture(scope="session")
def grey_small():
    return imageio.generate_test_image(24, 36, "grey", seed=1)


@pytest.fixture(scope="session")
def rgb_small():
    return imageio.generate_test_image(24, 36, "rgb", seed=2)


@pytest.fixture(scope="session")
def grey_odd():
    # Deliberately awkward dims: prime-ish, non-divisible by mesh shapes.
    return imageio.generate_test_image(37, 53, "grey", seed=3)


@pytest.fixture(scope="session")
def rgb_odd():
    return imageio.generate_test_image(41, 29, "rgb", seed=4)
