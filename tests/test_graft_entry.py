"""Keep the driver entry points working (compile-check + multichip dryrun)."""

import jax
import numpy as np

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == args[0].shape
    a = np.asarray(out)
    # u8-semantics invariant: exact integers in range
    assert ((a >= 0) & (a <= 255) & (a == np.rint(a))).all()


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_5():
    # non-power-of-two device count -> 1x5 grid
    graft.dryrun_multichip(5)
