"""Property-based tests: random filters/shapes/meshes vs the oracle.

The invariant under test is the framework's core contract (SURVEY.md §4):
    sharded(conv(x)) == serial_oracle(x)   bit-for-bit
for ANY odd filter, any image shape, any mesh that fits, any storage mode.
"""

import jax
import numpy as np
import pytest

# Optional dev dependency (pyproject `dev` extra): without it the module
# must SKIP, not break collection of the whole suite on minimal installs.
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from parallel_convolution_tpu.ops import filters as filters_lib, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio

MESHES = [(1, 1), (2, 2), (2, 4), (4, 1)]


@st.composite
def _case(draw):
    k = draw(st.sampled_from([3, 5]))
    # Integer taps over a power-of-two divisor: dyadic => exact f32, the
    # bit-exactness regime (non-dyadic filters are covered by fixed tests
    # with tolerance).
    taps = draw(
        st.lists(st.integers(-4, 8), min_size=k * k, max_size=k * k)
    )
    div = draw(st.sampled_from([1, 2, 4, 16]))
    H = draw(st.integers(k, 40))
    W = draw(st.integers(k, 48))
    mesh_shape = draw(st.sampled_from(MESHES))
    iters = draw(st.integers(1, 4))
    fuse = draw(st.sampled_from([1, 2]))
    storage = draw(st.sampled_from(["f32", "bf16"]))
    seed = draw(st.integers(0, 2**16))
    return k, taps, div, H, W, mesh_shape, iters, fuse, storage, seed


@given(_case())
@settings(max_examples=25, deadline=None)
def test_sharded_matches_oracle_random(case):
    k, taps, div, H, W, mesh_shape, iters, fuse, storage, seed = case
    filt = filters_lib.make_filter(
        "prop", np.array(taps, np.float32).reshape(k, k), divisor=div
    )
    R, C = mesh_shape
    r = filt.radius
    # skip infeasible combos instead of failing: block must fit halo depth
    if (H + R - 1) // R < r * fuse or (W + C - 1) // C < r * fuse:
        return
    img = imageio.generate_test_image(H, W, "grey", seed=seed)
    want = oracle.run_serial_u8(img, filt, iters)
    m = mesh_lib.make_grid_mesh(jax.devices()[: R * C], mesh_shape)
    x = img[None].astype(np.float32)
    out = step.sharded_iterate(x, filt, iters, mesh=m, quantize=True,
                               fuse=fuse, storage=storage)
    got = np.asarray(out)[0].astype(np.uint8)
    np.testing.assert_array_equal(got, want)
