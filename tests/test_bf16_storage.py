"""bf16 storage mode: half the bandwidth, still bit-exact for u8 images."""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


@pytest.mark.parametrize("backend", ["shifted", "xla_conv", "pallas"])
def test_bf16_bitexact_quantized(grey_odd, backend):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 6, mesh=_mesh((2, 4)),
                               quantize=True, backend=backend,
                               storage="bf16")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_bf16_rgb_gaussian5(rgb_odd):
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 3)
    x = imageio.interleaved_to_planar(rgb_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               quantize=True, storage="bf16")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_bf16_converge_quantized(grey_small):
    # convergence machinery under bf16 carries: exact integer diffs
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out_a, it_a = step.sharded_converge(x, filt, tol=0.5, max_iters=300,
                                        check_every=5, mesh=_mesh((2, 2)),
                                        quantize=True, storage="bf16")
    out_b, it_b = step.sharded_converge(x, filt, tol=0.5, max_iters=300,
                                        check_every=5, mesh=_mesh((2, 2)),
                                        quantize=True, storage="f32")
    assert it_a == it_b
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))


def test_bf16_model_api(grey_small):
    from parallel_convolution_tpu.models import ConvolutionModel

    m = ConvolutionModel(filt="blur3", mesh=_mesh((2, 2)), storage="bf16")
    got = m.run_image(grey_small, 5)
    want = oracle.run_serial_u8(grey_small, filters.get_filter("blur3"), 5)
    np.testing.assert_array_equal(got, want)
