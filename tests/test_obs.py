"""Round 11: unified observability layer.

Covers the acceptance surface of the obs PR:

* registry semantics (typed metrics, labels, get-or-create, shape guard);
* disabled mode (``PCTPU_OBS=0``): nothing recorded, near-zero overhead
  (the perf guard);
* event-log schema + atomic rotation with seq continuity;
* Prometheus exposition round-trip (render → parse) and the serving
  ``/metrics`` surfaces;
* exchange-byte accounting vs an independent analytic derivation, and
  the same numbers flowing out of ``iterate_prepared`` and bench rows;
* PhaseTimer thread-safety + tracing edge cases (nested-path collisions
  in ``to_row``, re-entrant phases, fence exceptions);
* supervisor ledger schema_version/heartbeat + tolerant old-ledger read.
"""

import json
import threading
import time

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.obs import attribution, events, metrics
from parallel_convolution_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Each test sees an enabled, empty registry and no global event log;
    the prior state is restored afterwards (other test modules rely on
    module-level counters accumulating silently)."""
    was_enabled = metrics.enabled()
    metrics.set_enabled(True)
    metrics.reset()
    events.deconfigure()
    yield
    events.deconfigure()
    metrics.reset()
    metrics.set_enabled(was_enabled)


# ------------------------------------------------------------- registry
def test_counter_gauge_histogram_semantics():
    c = metrics.counter("c_total", "x", ("who",))
    c.inc(who="a")
    c.inc(2.5, who="a")
    c.inc(who="b")
    assert c.value(who="a") == 3.5 and c.value(who="b") == 1.0
    with pytest.raises(ValueError):
        c.inc(-1, who="a")          # counters are monotonic
    with pytest.raises(ValueError):
        c.inc(nope="a")             # labels must match declaration

    g = metrics.gauge("g", "", ("k",))
    g.set(5, k="x")
    g.set(2, k="x")
    assert g.value(k="x") == 2.0    # last-write-wins
    g.max(7, k="x")
    g.max(3, k="x")
    assert g.value(k="x") == 7.0    # high-water mark

    h = metrics.histogram("h_seconds", "", (), buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    s = h._series_snapshot()[0]
    assert s["count"] == 4 and s["counts"] == [1, 1, 1, 1]
    assert s["sum"] == pytest.approx(5.555)
    assert 0.01 < h.quantile(0.5) <= 0.1
    # +Inf is the IMPLICIT last bucket: an explicit one would render a
    # duplicate le="+Inf" sample, so non-finite bounds are rejected.
    import math

    with pytest.raises(ValueError, match="finite"):
        metrics.histogram("h_bad", buckets=(1.0, math.inf))
    with pytest.raises(ValueError, match="finite"):
        metrics.histogram("h_bad2", buckets=())


def test_registry_get_or_create_and_shape_guard():
    a = metrics.counter("same_total", "", ("x",))
    b = metrics.counter("same_total", "", ("x",))
    assert a is b                   # handles converge on one series set
    with pytest.raises(ValueError):
        metrics.counter("same_total", "", ("y",))   # labelnames drifted
    with pytest.raises(ValueError):
        metrics.gauge("same_total")                 # kind drifted


def test_mirrored_stats_is_a_dict_and_a_gauge():
    g = metrics.gauge("stats_g", "", ("key",))
    ms = metrics.MirroredStats(g, initial={"hits": 0, "misses": 0})
    ms["hits"] += 3
    ms["misses"] = 7
    # The legacy dict surface is intact...
    assert dict(ms) == {"hits": 3, "misses": 7}
    assert ms["hits"] == 3 and len(ms) == 2 and set(ms) == {"hits", "misses"}
    # ...and the same values are registry series.
    assert g.value(key="hits") == 3.0 and g.value(key="misses") == 7.0


def test_mirrored_stats_dict_survives_disabled_mode():
    g = metrics.gauge("stats_g2", "", ("key",))
    ms = metrics.MirroredStats(g, initial={"n": 0})
    metrics.set_enabled(False)
    ms["n"] += 5
    assert ms["n"] == 5             # serving semantics never depend on obs
    assert g.value(key="n") == 0.0  # but the mirror went dark


# -------------------------------------------------------- disabled mode
def test_disabled_mode_records_nothing():
    metrics.set_enabled(False)
    c = metrics.counter("dark_total", "", ("a",))
    c.inc(a="x")
    metrics.histogram("dark_s").observe(1.0)
    metrics.gauge("dark_g").set(3)
    snap = metrics.snapshot()
    assert snap["enabled"] is False
    assert all(not m["series"] for m in snap["metrics"])
    # events.emit is also a no-op even with a log installed
    log = events.configure("/tmp/_pctpu_dark.jsonl")
    events.emit("retry", attempt=1)
    assert not log.path.exists() or log.path.stat().st_size == 0


def test_disabled_mode_overhead_is_near_zero():
    """The PCTPU_OBS=0 perf guard: a disabled inc must be one load + one
    branch.  Bounds are deliberately generous (CI jitter) — the test
    fails on a pathological regression (locking, allocation, formatting
    on the disabled path), not on scheduler noise."""
    c = metrics.counter("perf_total", "", ("a",))
    n = 50_000
    metrics.set_enabled(True)
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc(a="x")
    enabled_s = time.perf_counter() - t0
    metrics.set_enabled(False)
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc(a="x")
    disabled_s = time.perf_counter() - t0
    assert disabled_s < 0.5                      # < 10 µs/call, absolute
    assert disabled_s < enabled_s * 1.5 + 0.01   # never costlier than on
    assert c.value(a="x") == n                   # only the enabled half


# ------------------------------------------------------------ event log
def test_event_log_schema_and_unknown_kind(tmp_path):
    log = events.configure(tmp_path / "ev.jsonl")
    rec = events.emit("compile", backend="shifted")
    recs = events.read_events(log.path)
    assert len(recs) == 1
    assert events.validate_event(recs[0]) == []
    r = recs[0]
    assert r["seq"] == 1 and r["kind"] == "compile"
    assert isinstance(r["ts"], float) and isinstance(r["perf"], float)
    with pytest.raises(ValueError, match="unknown event kind"):
        log.emit("typo_kind")
    with pytest.raises(ValueError, match="reserved"):
        log.emit("compile", seq=99)
    # validate_event names each problem
    assert events.validate_event({"kind": "nope"})
    assert events.validate_event([1, 2]) == ["not an object: list"]


def test_event_log_rotation_atomic_and_seq_continuous(tmp_path):
    log = events.EventLog(tmp_path / "ev.jsonl", max_bytes=4096, keep=2)
    for i in range(300):
        log.emit("retry", attempt=i, pad="x" * 60)
    gens = log.generations()
    assert len(gens) == 3            # .2, .1, live — older gens dropped
    recs = events.read_events(log.path)
    # Stitched timeline: strictly consecutive seq, ending at the total.
    seqs = [r["seq"] for r in recs]
    assert seqs == list(range(seqs[0], 301))
    assert all(events.validate_event(r) == [] for r in recs)


def test_module_emit_without_log_is_noop():
    events.emit("retry", attempt=1)  # no log configured: must not raise


def test_event_log_survives_external_rotation(tmp_path):
    """A second process rotating the shared file must not leave this
    writer streaming into the renamed `.1` generation."""
    import os

    log = events.EventLog(tmp_path / "ev.jsonl")
    log.emit("retry", attempt=1)
    # Simulate the sibling's rotation: rename the live file away.
    os.replace(log.path, log.path.with_name("ev.jsonl.1"))
    log.emit("retry", attempt=2)
    live = events.read_events(log.path, include_rotated=False)
    assert [r["attempt"] for r in live] == [2]   # landed in the NEW live
    both = events.read_events(log.path)
    assert [r["attempt"] for r in both] == [1, 2]
    assert all(r["pid"] == os.getpid() for r in both)


def test_rotation_survives_sibling_stealing_live(tmp_path):
    """The writer's OWN rotation racing a sibling's: the live file can
    vanish between our size check and our ``os.replace`` (both processes
    rotate the same path).  Pre-round-13 this raised FileNotFoundError
    out of ``emit`` and LOST the line; now a vanished source degrades to
    'already moved' and the write proceeds in a fresh generation."""
    import os

    log = events.EventLog(tmp_path / "ev.jsonl", max_bytes=4096, keep=2)
    log.emit("retry", attempt=1)
    # The sibling wins the race: live is renamed away while we hold an
    # open fd and believe the file still exists.
    os.replace(log.path, tmp_path / "stolen.jsonl")
    with log._lock:
        log._rotate_locked()         # must not raise
    log.emit("retry", attempt=2)     # and the stream continues
    recs = events.read_events(log.path)
    assert [r["attempt"] for r in recs] == [2]
    stolen = events.read_events(tmp_path / "stolen.jsonl",
                                include_rotated=False)
    assert [r["attempt"] for r in stolen] == [1]   # nothing lost


def test_event_log_multithread_rotation_stress(tmp_path):
    """N writer threads across MANY forced rotations, with an external
    actor stealing the live file mid-stream (a sibling process's
    rotation): no writer may crash, no line may be lost, and each pid's
    seq stream must stay contiguous across every file the lines landed
    in."""
    import os

    # keep must exceed the WORST-CASE rotation count or the test races
    # its own mover thread: 6x200 lines x ~200 B / 4096 B/segment is up
    # to ~60 rotations, and with keep=50 a starved mover let the
    # writer's own (correct) rotation delete generation 51+ — a flaky
    # false failure on loaded machines.  120 gives 2x headroom while
    # still forcing dozens of rotations.
    log = events.EventLog(tmp_path / "ev.jsonl", max_bytes=4096,
                          keep=120)
    n_threads, n_lines = 6, 200
    stop = threading.Event()
    errors: list[BaseException] = []

    def mover():
        k = 0
        while not stop.is_set():
            try:
                os.replace(log.path, tmp_path / f"moved.{k}.stolen")
                k += 1
            except OSError:
                pass
            time.sleep(0.0002)

    def writer(w):
        try:
            for i in range(n_lines):
                log.emit("retry", attempt=i, w=w, pad="x" * 120)
        except BaseException as e:  # noqa: BLE001 — the assertion target
            errors.append(e)

    mv = threading.Thread(target=mover, daemon=True)
    mv.start()
    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    mv.join()
    assert errors == []
    recs = []
    for p in sorted(tmp_path.iterdir()):
        for n, line in enumerate(p.read_text().splitlines(), 1):
            if line.strip():
                recs.append(json.loads(line))
    assert all(events.validate_event(r) == [] for r in recs)
    # Zero lost lines, zero duplicates, per-pid seq contiguous: the
    # stitched multiset of seqs across EVERY generation + stolen file is
    # exactly 1..total.
    seqs = sorted(r["seq"] for r in recs)
    assert seqs == list(range(1, n_threads * n_lines + 1))


# ----------------------------------------------------------- exposition
def test_exposition_round_trip():
    c = metrics.counter("rt_total", "help text", ("name",))
    c.inc(3, name='we"ird\nlabel')
    h = metrics.histogram("rt_seconds", "", ("b",), buckets=(0.1, 1.0))
    h.observe(0.05, b="z")
    h.observe(5.0, b="z")
    text = metrics.render_text()
    assert "# TYPE rt_total counter" in text
    assert "# HELP rt_total help text" in text
    parsed = metrics.parse_text(text)
    assert parsed["rt_total"] == [({"name": 'we"ird\nlabel'}, 3.0)]
    # A literal backslash-n (repr'd exception text) must round-trip —
    # sequential unescape passes corrupted it to backslash-newline.
    c2 = metrics.counter("esc_total", "", ("cause",))
    c2.inc(cause='OSError("bad\\npath")')   # literal backslash + n
    reparsed = metrics.parse_text(metrics.render_text())
    assert reparsed["esc_total"] == [({"cause": 'OSError("bad\\npath")'},
                                      1.0)]
    buckets = {s[0]["le"]: s[1] for s in parsed["rt_seconds_bucket"]}
    assert buckets == {"0.1": 1.0, "1": 1.0, "+Inf": 2.0}  # cumulative
    assert parsed["rt_seconds_count"] == [({"b": "z"}, 2.0)]
    with pytest.raises(ValueError):
        metrics.parse_text("malformed{ 3")


def test_in_process_metrics_surface(monkeypatch):
    from parallel_convolution_tpu.serving import frontend

    metrics.counter("srv_total").inc()
    status, text = 200, frontend.metrics_text()
    assert "srv_total 1" in text
    metrics.set_enabled(False)
    assert frontend.metrics_text().startswith("#")  # still valid exposition


# ------------------------------------------- exchange-byte accounting
def test_halo_bytes_vs_independent_formula():
    # Independent derivation: zero boundary, R rows of C columns; the
    # row phase moves (R-1)*C slabs of (channels*d*bw*B) bytes each way;
    # the column phase moves (C-1)*R slabs cut from the ROW-PADDED block,
    # height bh+2d.
    grid, block, r, fuse, ch, B = (2, 4), (24, 16), 1, 2, 3, 4
    d = r * fuse
    bh, bw = block
    want_ns = (grid[0] - 1) * grid[1] * ch * d * bw * B
    want_ew = (grid[1] - 1) * grid[0] * ch * d * (bh + 2 * d) * B
    got = attribution.halo_bytes_per_round(grid, block, r, fuse, ch, "f32")
    assert got["north"] == got["south"] == want_ns
    assert got["east"] == got["west"] == want_ew
    assert got["total"] == 2 * (want_ns + want_ew)
    # bf16 halves every direction
    half = attribution.halo_bytes_per_round(grid, block, r, fuse, ch, "bf16")
    assert half["total"] * 2 == got["total"]
    # periodic closes the ring: R senders per axis instead of R-1
    per = attribution.halo_bytes_per_round(grid, block, r, fuse, ch, "f32",
                                           boundary="periodic")
    assert per["north"] == grid[0] * grid[1] * ch * d * bw * B
    # 1x1 mesh: no collective, no bytes
    assert attribution.halo_bytes_per_round(
        (1, 1), block, r, fuse, ch, "f32")["total"] == 0
    # a 1-long axis moves nothing even under periodic (identity wrap)
    one_row = attribution.halo_bytes_per_round(
        (1, 4), block, r, fuse, ch, "f32", boundary="periodic")
    assert one_row["north"] == 0 and one_row["east"] > 0


def test_halo_bytes_total_accounts_the_tail_round():
    # 10 iterations at fuse 4 = 2 full rounds (depth 4r) + 1 tail (2r).
    grid, block, r, ch = (2, 2), (32, 32), 1, 1
    full = attribution.halo_bytes_per_round(grid, block, r, 4, ch, "f32")
    tail = attribution.halo_bytes_per_round(grid, block, r, 2, ch, "f32")
    tot = attribution.halo_bytes_total(grid, block, r, 4, 10, ch, "f32")
    assert tot["rounds"] == 3
    for dname in attribution.DIRECTIONS:
        assert tot[dname] == 2 * full[dname] + tail[dname]


def test_iterate_prepared_feeds_halo_counters(grey_small):
    from parallel_convolution_tpu.ops import filters
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
    from parallel_convolution_tpu.utils import imageio

    m = mesh_lib.make_grid_mesh(jax.devices()[:8], (2, 4))
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    xs, valid_hw, block_hw = step._prepare(x, m, filt.radius)
    iters = 3
    step.iterate_prepared(xs, filt, iters, m, valid_hw)
    want = attribution.halo_bytes_total(
        (2, 4), block_hw, filt.radius, 1, iters, 1, "f32")
    c = metrics.counter("pctpu_halo_bytes_total", "", ("backend",
                                                       "direction"))
    for dname in attribution.DIRECTIONS:
        assert c.value(backend="shifted", direction=dname) == want[dname]
    assert metrics.counter(
        "pctpu_iterations_total", "",
        ("backend",)).value(backend="shifted") == iters
    # iterate_prepared dispatches async, so it must NOT feed wall-based
    # series (that would require a serializing fence) — byte/round
    # counters only.  Wall series come from fenced call sites (bench,
    # serving, converge).
    h = metrics.histogram("pctpu_step_seconds", "", ("backend",))
    assert h.quantile(0.5, backend="shifted") is None
    step.sharded_converge(
        imageio.interleaved_to_planar(grey_small).astype(np.float32),
        filt, 1e-3, 4, check_every=2, mesh=m)
    assert h.quantile(0.5, backend="shifted") is not None  # fenced caller


def test_bench_row_carries_attribution(grey_small):
    from parallel_convolution_tpu.ops import filters
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.utils import bench

    m = mesh_lib.make_grid_mesh(jax.devices()[:8], (2, 4))
    row = bench.bench_iterate((48, 64), filters.get_filter("blur3"), 2,
                              mesh=m, reps=1)
    assert 0.0 <= row["exchange_fraction"] <= 1.0
    hb = row["halo_bytes"]
    want = attribution.halo_bytes_total(
        (2, 4), (24, 16), 1, row["fuse"], 2, 1, "f32")
    assert {d: hb[d] for d in attribution.DIRECTIONS} == {
        d: want[d] for d in attribution.DIRECTIONS}
    # the drift series landed, labeled with the tuning plan key
    snap = metrics.snapshot()
    drift = [mm for mm in snap["metrics"]
             if mm["name"] == "pctpu_plan_drift_ratio"][0]
    assert drift["series"] and all(
        s["labels"]["backend"] == "shifted" for s in drift["series"])


# --------------------------------------------------- PhaseTimer hardening
def test_phase_timer_thread_safety():
    """A timer SHARED across threads (the batcher-worker + HTTP-handler
    shape) must keep per-thread nesting and exact counts — pre-round-11
    the shared ``_stack`` interleaved and corrupted paths."""
    t = tracing.PhaseTimer()
    n_threads, n_iter = 8, 200
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(n_iter):
            with t.phase("outer"):
                with t.phase("inner"):
                    pass

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    # Exactly two paths — any stack interleaving would have minted paths
    # like outer/outer or inner/outer.
    assert set(t.walls) == {"outer", "outer/inner"}
    assert t.counts["outer"] == n_threads * n_iter
    assert t.counts["outer/inner"] == n_threads * n_iter


def test_phase_timer_to_row_collision_sums():
    t = tracing.PhaseTimer()
    with t.phase("a"):
        with t.phase("b"):
            time.sleep(0.001)
    with t.phase("a_b"):   # flattens to the same row key as a/b
        time.sleep(0.001)
    row = t.to_row()
    assert set(row) == {"phase_a_s", "phase_a_b_s"}
    # summed, not overwritten: the collided key carries BOTH walls
    assert row["phase_a_b_s"] == pytest.approx(
        t.wall("a/b") + t.wall("a_b"), abs=1e-5)


def test_phase_timer_reentrant_same_name():
    t = tracing.PhaseTimer()
    with t.phase("x"):
        with t.phase("x"):
            pass
    assert set(t.walls) == {"x", "x/x"}
    assert t.counts["x"] == 1 and t.counts["x/x"] == 1


def test_phase_timer_fence_exception_leaves_stack_balanced():
    t = tracing.PhaseTimer()
    dead = jax.numpy.ones((4,))
    dead.delete()
    with pytest.raises(RuntimeError):
        with t.phase("outer"):
            with t.phase("inner", fence=dead):
                pass
    # Both phases recorded despite the fence raising, and the stack is
    # balanced: the next phase lands top-level, not under a ghost parent.
    assert t.counts["outer"] == 1 and t.counts["outer/inner"] == 1
    with t.phase("after"):
        pass
    assert "after" in t.walls and "outer/after" not in t.walls


# -------------------------------------------------- supervisor ledger
def test_supervisor_ledger_schema_and_heartbeat(tmp_path):
    import sys

    from parallel_convolution_tpu.resilience.retry import RetryPolicy
    from parallel_convolution_tpu.resilience.supervisor import (
        LEDGER_SCHEMA, Leg, Supervisor, read_ledger,
    )

    touches = []

    class Spy(Supervisor):
        def _touch_heartbeat(self, leg_name=""):
            touches.append(leg_name)
            super()._touch_heartbeat(leg_name)

    leg = Leg(name="nap",
              cmd=[sys.executable, "-c", "import time; time.sleep(0.8)"])
    sup = Spy([leg], tmp_path / "state",
              policy=RetryPolicy(max_attempts=1), sleep=lambda s: None,
              log=lambda m: None, heartbeat_every=0.2)
    assert sup.run() == 0
    ledger = read_ledger(tmp_path / "state" / "status.json")
    assert ledger["schema_version"] == LEDGER_SCHEMA
    assert ledger["heartbeat"] and ledger["heartbeat_unix"] > 0
    assert ledger["legs"]["nap"]["state"] == "done"
    # The heartbeat was refreshed BETWEEN polls while the leg slept — the
    # running-vs-hung watcher signal.
    assert len(touches) >= 2


def test_read_ledger_tolerates_old_schema(tmp_path):
    from parallel_convolution_tpu.resilience.supervisor import read_ledger

    old = {"legs": {"a": {"state": "done"}}, "halt": None,
           "updated": "2026-01-01T00:00:00Z"}
    p = tmp_path / "status.json"
    p.write_text(json.dumps(old))
    got = read_ledger(p)
    assert got["schema_version"] == 1          # pre-round-11 default
    assert got["heartbeat"] == "2026-01-01T00:00:00Z"  # best old signal
    assert got["heartbeat_unix"] is None
    with pytest.raises(FileNotFoundError):
        read_ledger(tmp_path / "missing.json")


# ----------------------------------------------- resilience telemetry
def test_retry_and_fault_telemetry(tmp_path):
    from parallel_convolution_tpu.resilience import faults
    from parallel_convolution_tpu.resilience.retry import (
        RetryPolicy, with_retry,
    )

    events.configure(tmp_path / "ev.jsonl")
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("blip")
        return "ok"

    assert with_retry(flaky, RetryPolicy(max_attempts=5, base_delay=0.0),
                      sleep=lambda s: None) == "ok"
    assert metrics.counter(
        "pctpu_retries_total", "",
        ("error",)).value(error="TimeoutError") == 2

    with faults.injected("io_read:1"):
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("io_read")
    assert metrics.counter(
        "pctpu_faults_fired_total", "",
        ("site",)).value(site="io_read") == 1

    kinds = [r["kind"] for r in events.read_events(tmp_path / "ev.jsonl")]
    assert kinds.count("retry") == 2 and "fault_trigger" in kinds


def test_quarantine_counter_names_cause(tmp_path, grey_small):
    from parallel_convolution_tpu.ops import filters
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
    from parallel_convolution_tpu.utils import checkpoint, imageio

    m = mesh_lib.make_grid_mesh(jax.devices()[:4], (2, 2))
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    xs, valid_hw, _ = step._prepare(x, m, filt.radius)
    checkpoint.save_state(tmp_path, xs, {
        "grid": [2, 2], "shape": list(xs.shape), "iters_done": 4,
        "valid_hw": list(valid_hw)})
    (tmp_path / "it_00000004" / "shard_0_0.npy").unlink()  # damage it
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.load_state(tmp_path, m)
    assert metrics.counter(
        "pctpu_quarantines_total", "",
        ("cause",)).value(cause="missing") == 1
    # a clean save left its duration/bytes series behind
    assert metrics.counter(
        "pctpu_checkpoint_bytes_total", "", ("op",)).value(op="save") > 0


# ------------------------------------------------------ serving spine
def test_service_stats_flow_through_registry(grey_small):
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request,
    )

    m = mesh_lib.make_grid_mesh(jax.devices()[:8], (2, 4))
    svc = ConvolutionService(m)
    try:
        r = svc.submit(Request(image=grey_small, iters=2))
        assert r.ok
        bad = svc.submit(Request(image=grey_small.astype(np.float32)))
        assert not bad.ok and bad.reason == "invalid"
    finally:
        svc.close()
    # One spine: the legacy dicts and the registry agree.
    g = metrics.gauge("pctpu_service_stats", "", ("key",))
    assert g.value(key="completed") == svc.stats["completed"] == 1
    assert g.value(key="rejected_invalid") == 1
    adm = metrics.counter("pctpu_admission_total", "", ("outcome",))
    assert adm.value(outcome="completed") == 1
    assert adm.value(outcome="invalid") == 1
    eng = metrics.gauge("pctpu_engine_stats", "", ("key",))
    assert eng.value(key="compiles") == svc.engine.stats["compiles"]
    # per-request phase histogram has every serving phase
    h = metrics.histogram("pctpu_request_phase_seconds", "",
                          ("phase", "backend"))
    for phase in ("queue", "compile", "device", "copy_in", "copy_out",
                  "total"):
        assert h.quantile(0.5, phase=phase, backend="shifted") is not None
