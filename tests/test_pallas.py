"""Pallas stencil kernel vs the oracle (interpret mode on the CPU backend)."""

import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle, pallas_stencil
from parallel_convolution_tpu.utils import imageio


@pytest.mark.parametrize("name", ["blur3", "gaussian5", "edge3", "edge5"])
@pytest.mark.parametrize("fixture", ["grey_small", "rgb_small"])
def test_kernel_bitexact_vs_oracle(request, fixture, name):
    img = request.getfixturevalue(fixture)
    filt = filters.get_filter(name)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(pallas_stencil.correlate_shifted_pallas(x, filt))
    want = oracle.correlate_once(img.astype(np.float32), filt)
    want = imageio.interleaved_to_planar(want)
    np.testing.assert_array_equal(got, want)


def test_kernel_multi_tile_grid():
    # Image larger than one tile in both dims → multi-program grid with
    # double-buffered DMA handoff across tiles (tile clamped small here).
    img = imageio.generate_test_image(40, 300, "grey", seed=13)
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(
        pallas_stencil.correlate_shifted_pallas(x, filt, tile=(16, 128))
    )
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(img.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_kernel_rgb_multi_channel_grid():
    img = imageio.generate_test_image(20, 150, "rgb", seed=14)
    filt = filters.get_filter("gaussian5")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(
        pallas_stencil.correlate_shifted_pallas(x, filt, tile=(8, 128))
    )
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(img.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_kernel_odd_nonaligned_shape(grey_odd):
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    got = np.asarray(pallas_stencil.correlate_shifted_pallas(x, filt))
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(grey_odd.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_sharded_pallas_backend(grey_odd):
    # Pallas kernel composed under shard_map: full distributed pipeline.
    from parallel_convolution_tpu.parallel import step
    import jax
    from parallel_convolution_tpu.parallel import mesh as mesh_lib

    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 3)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    m = mesh_lib.make_grid_mesh(jax.devices()[:4], (2, 2))
    out = step.sharded_iterate(x, filt, 3, mesh=m, backend="pallas")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_magic_round_identity_dense():
    # The magic-number round ((x + 1.5*2^23) - 1.5*2^23) must equal
    # np.rint (half-to-even) on a dense grid covering the quantize-mode
    # range, INCLUDING exact .5 ties — under XLA, where the naive form
    # would be algebraically folded away (measured on XLA:CPU: the round
    # vanished entirely); the optimization_barrier form must survive.
    import jax
    import jax.numpy as jnp

    xs = np.arange(-4.0 * 16, 260.0 * 16, dtype=np.float32) / 16.0  # .0625 grid
    ties = np.arange(-4.0, 260.0, dtype=np.float32) + 0.5            # all ties
    for v in (xs, ties):
        got = np.asarray(jax.jit(
            lambda x: jax.lax.optimization_barrier(
                x + pallas_stencil._MAGIC) - pallas_stencil._MAGIC
        )(jnp.asarray(v)))
        np.testing.assert_array_equal(got, np.rint(v))


BLUR_TAPS = (0.0625, 0.125, 0.0625, 0.125, 0.25, 0.125,
             0.0625, 0.125, 0.0625)


def test_round_mode_selection(monkeypatch):
    # Seed the compiled-magic byte-guard as passed: this test pins the
    # SELECTOR logic; the guard itself (which would launch a real compiled
    # probe kernel here) has its own tests below.
    monkeypatch.setattr(pallas_stencil, "_MAGIC_GUARD",
                        {"ok": True, "probing": False})
    assert pallas_stencil._round_mode_for(BLUR_TAPS, interpret=True) == \
        "magic_barrier"
    assert pallas_stencil._round_mode_for(BLUR_TAPS, interpret=False) == \
        "magic"
    # A filter whose accumulator bound 255*L1 could leave the magic
    # form's exact range falls back to rint.
    huge = (9000.0,) * 9
    assert pallas_stencil._round_mode_for(huge, interpret=False) == "rint"
    assert pallas_stencil._round_mode_for(huge, interpret=True) == "rint"


def test_magic_guard_mismatch_falls_back(monkeypatch):
    """Library-level magic-round byte-guard (ADVICE r5): a forced probe
    MISMATCH must flip every compiled build to rint, warn loudly, and
    cache the verdict so the probe runs once per process."""
    calls = []

    def probe():
        calls.append(1)
        return False

    monkeypatch.setattr(pallas_stencil, "_probe_magic_round", probe)
    monkeypatch.setattr(pallas_stencil, "_MAGIC_GUARD",
                        {"ok": None, "probing": False})
    with pytest.warns(RuntimeWarning, match="magic-round byte-guard"):
        assert pallas_stencil._round_mode_for(
            BLUR_TAPS, interpret=False) == "rint"
    # A real byte mismatch is recorded as such — the terminal condition
    # automation (bench.py magic_round_guard) keys on this cause.
    assert pallas_stencil._MAGIC_GUARD["cause"] == "mismatch"
    # Cached per process: the second compiled build must not re-probe.
    assert pallas_stencil._round_mode_for(
        BLUR_TAPS, interpret=False) == "rint"
    assert len(calls) == 1
    # Interpret-mode kernels use the barrier form and never consult the
    # compiled guard.
    assert pallas_stencil._round_mode_for(
        BLUR_TAPS, interpret=True) == "magic_barrier"


def test_magic_guard_probe_failure_falls_back(monkeypatch):
    """A probe that ERRORS (not just mismatches) leaves bytes unverified:
    same conservative rint fallback, same warning channel."""

    def probe():
        raise RuntimeError("no accelerator")

    monkeypatch.setattr(pallas_stencil, "_probe_magic_round", probe)
    monkeypatch.setattr(pallas_stencil, "_MAGIC_GUARD",
                        {"ok": None, "probing": False})
    with pytest.warns(RuntimeWarning, match="probe failed"):
        assert pallas_stencil._round_mode_for(
            BLUR_TAPS, interpret=False) == "rint"
    # A crashed probe is NOT a detected fold: the cause stays distinct so
    # automation treats it as retryable, never terminal.
    assert pallas_stencil._MAGIC_GUARD["cause"] == "probe-error"


def test_magic_guard_pass_keeps_magic(monkeypatch):
    monkeypatch.setattr(pallas_stencil, "_probe_magic_round", lambda: True)
    monkeypatch.setattr(pallas_stencil, "_MAGIC_GUARD",
                        {"ok": None, "probing": False})
    assert pallas_stencil._round_mode_for(
        BLUR_TAPS, interpret=False) == "magic"


def test_magic_guard_probe_recursion_breaks(monkeypatch):
    """While the probe's own kernel builds, the guard must report magic
    (the form under test) instead of recursing into another probe."""
    monkeypatch.setattr(pallas_stencil, "_MAGIC_GUARD",
                        {"ok": None, "probing": True})
    assert pallas_stencil._compiled_magic_ok() is True


def test_quantize_acc_modes_agree():
    # All three round modes compute the same function on quantize-range
    # accs (interpret/XLA path uses the barrier form, Mosaic the bare
    # form; silicon agreement is recorded in evidence/round_mode_ab_r5).
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    acc = rng.uniform(-2.0, 258.0, 4096).astype(np.float32)
    acc[:512] = np.arange(512, dtype=np.float32) * 0.5  # exact ties
    outs = {}
    for mode in ("rint", "magic_barrier"):
        outs[mode] = np.asarray(jax.jit(
            lambda a, m=mode: pallas_stencil._quantize_acc(a, False, m)
        )(jnp.asarray(acc)))
    np.testing.assert_array_equal(outs["rint"], outs["magic_barrier"])
