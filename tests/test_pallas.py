"""Pallas stencil kernel vs the oracle (interpret mode on the CPU backend)."""

import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle, pallas_stencil
from parallel_convolution_tpu.utils import imageio


@pytest.mark.parametrize("name", ["blur3", "gaussian5", "edge3", "edge5"])
@pytest.mark.parametrize("fixture", ["grey_small", "rgb_small"])
def test_kernel_bitexact_vs_oracle(request, fixture, name):
    img = request.getfixturevalue(fixture)
    filt = filters.get_filter(name)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(pallas_stencil.correlate_shifted_pallas(x, filt))
    want = oracle.correlate_once(img.astype(np.float32), filt)
    want = imageio.interleaved_to_planar(want)
    np.testing.assert_array_equal(got, want)


def test_kernel_multi_tile_grid():
    # Image larger than one tile in both dims → multi-program grid with
    # double-buffered DMA handoff across tiles (tile clamped small here).
    img = imageio.generate_test_image(40, 300, "grey", seed=13)
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(
        pallas_stencil.correlate_shifted_pallas(x, filt, tile=(16, 128))
    )
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(img.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_kernel_rgb_multi_channel_grid():
    img = imageio.generate_test_image(20, 150, "rgb", seed=14)
    filt = filters.get_filter("gaussian5")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    got = np.asarray(
        pallas_stencil.correlate_shifted_pallas(x, filt, tile=(8, 128))
    )
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(img.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_kernel_odd_nonaligned_shape(grey_odd):
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    got = np.asarray(pallas_stencil.correlate_shifted_pallas(x, filt))
    want = imageio.interleaved_to_planar(
        oracle.correlate_once(grey_odd.astype(np.float32), filt)
    )
    np.testing.assert_array_equal(got, want)


def test_sharded_pallas_backend(grey_odd):
    # Pallas kernel composed under shard_map: full distributed pipeline.
    from parallel_convolution_tpu.parallel import step
    import jax
    from parallel_convolution_tpu.parallel import mesh as mesh_lib

    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 3)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    m = mesh_lib.make_grid_mesh(jax.devices()[:4], (2, 2))
    out = step.sharded_iterate(x, filt, 3, mesh=m, backend="pallas")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)
