"""u8 storage tier: the reference's own ``unsigned char`` carry dtype.

Quantized states are exact integers <= 255, so uint8 carries between
iterations lose nothing while quartering HBM/ICI traffic vs f32 (and
halving vs bf16) — accumulation stays f32 inside every correlate
implementation.  All paths must remain bit-identical to the serial oracle
(reference validation contract, SURVEY.md §4 golden-output comparison).
"""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


@pytest.mark.parametrize("backend", ["shifted", "xla_conv", "separable",
                                     "pallas", "pallas_sep"])
def test_u8_bitexact_quantized(grey_odd, backend):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 6, mesh=_mesh((2, 4)),
                               quantize=True, backend=backend, storage="u8")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (4, 2), (1, 8)])
def test_u8_mesh_shapes(grey_odd, mesh_shape):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 4)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 4, mesh=_mesh(mesh_shape),
                               quantize=True, storage="u8")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_u8_rgb_radius2(rgb_odd):
    # radius-2 filter exercises the 2-deep halo exchange on u8 carries
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 3)
    x = imageio.interleaved_to_planar(rgb_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               quantize=True, storage="u8")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("fuse", [2, 3])
def test_u8_temporal_fusion(grey_odd, fuse):
    # fused Pallas path: u8 HBM windows, f32 VMEM intermediates, u8 out
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 6, mesh=_mesh((2, 2)),
                               quantize=True, backend="pallas_sep",
                               storage="u8", fuse=fuse)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_u8_periodic(grey_small):
    # 24x36 divides a 2x2 grid exactly -> torus wrap is legal
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    a = step.sharded_iterate(x, filt, 4, mesh=_mesh((2, 2)), quantize=True,
                             storage="u8", boundary="periodic")
    b = step.sharded_iterate(x, filt, 4, mesh=_mesh((2, 2)), quantize=True,
                             storage="f32", boundary="periodic")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_u8_converge_matches_f32(grey_small):
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out_a, it_a = step.sharded_converge(x, filt, tol=0.5, max_iters=300,
                                        check_every=5, mesh=_mesh((2, 2)),
                                        quantize=True, storage="u8")
    out_b, it_b = step.sharded_converge(x, filt, tol=0.5, max_iters=300,
                                        check_every=5, mesh=_mesh((2, 2)),
                                        quantize=True, storage="f32")
    assert it_a == it_b
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))


def test_u8_requires_quantize(grey_small):
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    with pytest.raises(ValueError, match="quantize"):
        step.sharded_iterate(x, filters.get_filter("blur3"), 2,
                             mesh=_mesh((1, 1)), quantize=False, storage="u8")
    with pytest.raises(ValueError, match="quantize"):
        step.sharded_converge(x, filters.get_filter("blur3"), tol=0.5,
                              max_iters=5, mesh=_mesh((1, 1)),
                              quantize=False, storage="u8")


def test_u8_iterate_prepared_guard(grey_small):
    # the public zero-copy entry must enforce the same quantize guard
    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    xs, valid_hw, _ = step._prepare(x, mesh, filt.radius, "u8")
    with pytest.raises(ValueError, match="quantize"):
        step.iterate_prepared(xs, filt, 2, mesh, valid_hw, quantize=False)


def test_u8_config_validation():
    from parallel_convolution_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="quantize"):
        RunConfig(rows=8, cols=8, storage="u8", quantize=False)
    cfg = RunConfig(rows=8, cols=8, storage="u8")
    assert cfg.storage == "u8"


def test_u8_model_api(grey_small):
    from parallel_convolution_tpu.models import ConvolutionModel

    m = ConvolutionModel(filt="blur3", mesh=_mesh((2, 2)), storage="u8")
    got = m.run_image(grey_small, 5)
    want = oracle.run_serial_u8(grey_small, filters.get_filter("blur3"), 5)
    np.testing.assert_array_equal(got, want)


def test_u8_nonconvex_filter_keeps_clip(grey_odd):
    # sharpen3 has negative taps (not convex) → the kernels must keep the
    # [0, 255] clamp; on real images sharpening over/undershoots, so this
    # exercises clipping being LIVE, not just present.
    filt = filters.get_filter("sharpen3")
    want = oracle.run_serial_u8(grey_odd, filt, 4)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    for backend in ("pallas", "pallas_sep"):
        out = step.sharded_iterate(x, filt, 4, mesh=_mesh((2, 2)),
                                   quantize=True, backend=backend,
                                   storage="u8")
        got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
        np.testing.assert_array_equal(got, want)


def test_u8_convex_saturated_image_stays_in_range():
    # All-255 input through a NON-dyadic convex filter (gaussian taps do
    # not sum to exactly 1.0 in f32): the elided-clip path must still
    # produce bytes <= 255 — the convexity proof's boundary case.
    img = np.full((40, 56), 255, dtype=np.uint8)
    filt = filters.gaussian(5, 1.2)
    assert filt.convex and not filt.dyadic
    want = oracle.run_serial_u8(img, filt, 5)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    for fuse in (1, 5):
        out = step.sharded_iterate(x, filt, 5, mesh=_mesh((2, 2)),
                                   quantize=True, backend="pallas_sep",
                                   storage="u8", fuse=fuse)
        got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
        np.testing.assert_array_equal(got, want)


def test_quantize_contract_out_of_range_raises():
    # ADVICE r4: with a convex filter the store-back clamp is elided, so a
    # float plane outside [0, 255] must be rejected up front instead of
    # silently propagating unclamped.
    filt = filters.get_filter("blur3")
    x = np.full((1, 16, 24), 300.0, dtype=np.float32)
    with pytest.raises(ValueError, match="outside the u8 contract"):
        step.sharded_iterate(x, filt, 2, mesh=_mesh((2, 2)), quantize=True)
    with pytest.raises(ValueError, match="outside the u8 contract"):
        step.sharded_converge(x, filt, tol=0.5, max_iters=4, quantize=True,
                              mesh=_mesh((2, 2)))
    # Non-convex filters keep the live clamp -> unchanged behavior, no error.
    sharp = filters.get_filter("sharpen3")
    step.sharded_iterate(x, sharp, 1, mesh=_mesh((2, 2)), quantize=True)
    # In-contract input through a convex filter: untouched fast path.
    ok = np.full((1, 16, 24), 128.0, dtype=np.float32)
    step.sharded_iterate(ok, filt, 1, mesh=_mesh((2, 2)), quantize=True)
