import json

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import checkpoint, imageio, tracing


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def _prepare(img, m, filt):
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    return step._prepare(x, m, filt.radius)


def test_checkpointed_run_bitexact(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 4))
    xs, valid_hw, _ = _prepare(grey_odd, m, filt)
    out = checkpoint.run_checkpointed(
        xs, filt, total_iters=10, mesh=m, valid_hw=valid_hw,
        ckpt_dir=tmp_path / "ck", every=3,
    )
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    want = oracle.run_serial_u8(grey_odd, filt, 10)
    np.testing.assert_array_equal(got[0], want)
    # intermediate snapshots were written (at 3, 6, 9 but not 10)
    meta = checkpoint.load_meta(tmp_path / "ck")
    assert meta["iters_done"] == 9


def test_checkpoint_resume_continues(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    xs, valid_hw, _ = _prepare(grey_odd, m, filt)
    ck = tmp_path / "ck"
    # Simulate a killed run: snapshot at iteration 4 by hand.
    mid = step.iterate_prepared(xs, filt, 4, m, valid_hw)
    checkpoint.save_state(ck, mid, {
        "filter": filt.name, "quantize": True, "backend": "shifted",
        "fuse": 1, "boundary": "zero",
        "valid_hw": list(valid_hw), "grid": [2, 2],
        "iters_done": 4, "shape": list(mid.shape),
    })
    # Resume with xs=None: must pick up at 4 and finish 10 total.
    out = checkpoint.run_checkpointed(
        None, filt, total_iters=10, mesh=m, valid_hw=valid_hw,
        ckpt_dir=ck, every=4,
    )
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    want = oracle.run_serial_u8(grey_odd, filt, 10)
    np.testing.assert_array_equal(got[0], want)


def test_checkpoint_config_mismatch_raises(tmp_path, grey_small):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    xs, valid_hw, _ = _prepare(grey_small, m, filt)
    ck = tmp_path / "ck"
    checkpoint.save_state(ck, xs, {
        "filter": "edge3", "quantize": True, "backend": "shifted",
        "valid_hw": list(valid_hw), "grid": [2, 2],
        "iters_done": 2, "shape": list(xs.shape),
    })
    with pytest.raises(ValueError, match="config mismatch"):
        checkpoint.run_checkpointed(None, filt, 10, m, valid_hw, ck, 2)


def test_checkpoint_grid_mismatch_reshards(tmp_path, grey_small):
    # Round 10 (elastic recovery): a grid mismatch is no longer an error —
    # the snapshot reshards onto the requested mesh, bytes unchanged.
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    xs, valid_hw, _ = _prepare(grey_small, m, filt)
    checkpoint.save_state(tmp_path, xs, {
        "grid": [2, 2], "shape": list(xs.shape), "iters_done": 0,
        "valid_hw": list(valid_hw),
    })
    with pytest.warns(checkpoint.CheckpointWarning, match="resharding"):
        arr, meta = checkpoint.load_state(tmp_path, _mesh((1, 4)))
    assert meta["resharded_from"] == [2, 2] and meta["grid"] == [1, 4]
    np.testing.assert_array_equal(
        np.asarray(arr)[:, : valid_hw[0], : valid_hw[1]],
        np.asarray(xs)[:, : valid_hw[0], : valid_hw[1]])


def test_phase_timer(tmp_path):
    t = tracing.PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b", fence=jax.numpy.ones((4,))):
        pass
    rep = t.report()
    assert rep["phases"]["a"]["calls"] == 2
    assert set(rep["phases"]) == {"a", "b"}
    t.dump(tmp_path / "t.json")
    assert json.loads((tmp_path / "t.json").read_text())["total_s"] >= 0


def test_checkpoint_snapshots_pruned_and_crash_safe(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    xs, valid_hw, _ = _prepare(grey_odd, m, filt)
    ck = tmp_path / "ck"
    checkpoint.run_checkpointed(xs, filt, total_iters=10, mesh=m,
                                valid_hw=valid_hw, ckpt_dir=ck, every=2)
    snaps = sorted(p.name for p in ck.iterdir()
                   if p.is_dir() and p.name.startswith("it_"))
    # snapshots at 2,4,6,8 -> pruned to the last KEEP_SNAPSHOTS
    assert snaps == ["it_00000006", "it_00000008"]
    assert (ck / "LATEST").read_text().strip() == "it_00000008"
    # a torn newer snapshot (no meta yet) must not be picked up
    torn = ck / "it_00000010"
    torn.mkdir()
    (torn / "shard_0_0.npy").write_bytes(b"garbage")
    meta = checkpoint.load_meta(ck)
    assert meta["iters_done"] == 8
