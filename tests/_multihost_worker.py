"""Worker process for the two-process multi-host test (run via subprocess).

One OS process per "host", exactly the reference's ``mpiexec -np 2`` tier
(SURVEY.md §3.2 process boundary): ``jax.distributed.initialize`` is the
``MPI_Init``, each process owns 4 virtual CPU devices (set via XLA_FLAGS by
the launching test), and the 8-device mesh spans both processes — so the
halo ``ppermute`` and convergence ``psum`` really cross a process boundary,
and sharded I/O + checkpointing really run with only-my-shards
addressability.

argv: process_id num_processes coordinator_port workdir
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    pid, n = int(sys.argv[1]), int(sys.argv[2])
    port, work = sys.argv[3], sys.argv[4]

    from parallel_convolution_tpu.utils.platform import force_platform

    force_platform("cpu")

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n,
        process_id=pid,
    )

    import numpy as np

    from parallel_convolution_tpu.ops import filters, oracle
    from parallel_convolution_tpu.parallel import mesh as mesh_lib, multihost
    from parallel_convolution_tpu.utils import checkpoint, imageio, sharded_io

    info = multihost.process_info()
    assert info["process_count"] == n, info
    assert info["local_devices"] * n == info["global_devices"], info

    mesh = mesh_lib.make_grid_mesh(jax.devices())
    rows, cols = 37, 53  # non-divisible odd shape
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(rows, cols, "grey", seed=3)
    src = os.path.join(work, "in.raw")
    dst = os.path.join(work, "out.raw")
    ckpt = os.path.join(work, "ckpt")

    if pid == 0:
        imageio.write_raw(src, img)
    multihost.barrier("input-written")

    # Sharded load → checkpointed sharded iterate → sharded save, all with
    # per-process addressability (each process touches only its shards).
    xs = sharded_io.load_sharded(src, rows, cols, "grey", mesh)
    out = checkpoint.run_checkpointed(
        xs, filt, 4, mesh, (rows, cols), ckpt_dir=ckpt, every=2)

    if pid == 0:
        imageio.allocate_raw(dst, rows, cols, "grey")
    multihost.barrier("output-allocated")
    sharded_io.save_sharded(dst, out, rows, cols, "grey", allocate=False)
    multihost.barrier("output-saved")

    # Resume leg: LATEST points at iteration 2 (the final state is the
    # caller's to persist), so a fresh run with xs=None must reload the
    # cross-process per-shard snapshot and land bit-identical.
    out2 = checkpoint.run_checkpointed(
        None, filt, 4, mesh, (rows, cols), ckpt_dir=ckpt, every=2)
    local_same = all(
        np.array_equal(np.asarray(a.data), np.asarray(b.data))
        for a, b in zip(out.addressable_shards, out2.addressable_shards)
    )

    # Cross-host agreement on a host-side scalar (rank-0 wins).
    bcast = multihost.broadcast_scalar(float(pid + 7))

    if pid == 0:
        got = imageio.read_raw(dst, rows, cols, "grey")
        want = oracle.run_serial_u8(img, filt, 4)
        result = {
            "ok": bool(np.array_equal(got, want)) and local_same
            and bcast == 7.0,
            "bitexact_output": bool(np.array_equal(got, want)),
            "resume_bitexact_local": local_same,
            "broadcast": bcast,
            **info,
        }
        with open(os.path.join(work, "result.json"), "w") as f:
            json.dump(result, f)
    else:
        # Non-zero ranks report their legs through their exit code path.
        assert local_same and bcast == 7.0, (local_same, bcast)
    multihost.barrier("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
