"""Smoke tests for the benchmark module (C10) on the CPU mesh."""

import jax

from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.utils import bench


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def test_bench_iterate_reports():
    row = bench.bench_iterate((64, 128), get_filter("blur3"), 3,
                              mesh=_mesh((2, 2)), reps=1)
    assert row["devices"] == 4 and row["mesh"] == "2x2"
    assert row["gpixels_per_s"] > 0
    assert abs(row["gpixels_per_s"] / 4 - row["gpixels_per_s_per_chip"]) < 0.01


def test_bench_halo_p50():
    row = bench.bench_halo_p50((32, 128), r=1, mesh=_mesh((2, 2)), trials=5)
    assert row["p50_us"] > 0 and row["p90_us"] >= row["p50_us"]
    assert row["block"] == "32x128"


def test_bench_oracle_proxy_small():
    row = bench.bench_oracle_proxy((64, 64), iters=1)
    assert row["gpixels_per_s"] > 0
    assert row["impl"] in ("cpp-serial", "numpy-oracle")


def test_wall_returns_median():
    calls = []

    def fn(x):
        calls.append(1)
        return x

    t = bench.wall(fn, jax.numpy.ones((4,)), warmup=1, reps=3)
    assert t >= 0 and len(calls) == 4
