"""Smoke tests for the benchmark module (C10) on the CPU mesh."""

import jax

from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.utils import bench


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def test_bench_iterate_reports():
    row = bench.bench_iterate((64, 128), get_filter("blur3"), 3,
                              mesh=_mesh((2, 2)), reps=1)
    assert row["devices"] == 4 and row["mesh"] == "2x2"
    assert row["gpixels_per_s"] > 0
    assert abs(row["gpixels_per_s"] / 4 - row["gpixels_per_s_per_chip"]) < 0.01


def test_bench_halo_p50():
    row = bench.bench_halo_p50((32, 128), r=1, mesh=_mesh((2, 2)), trials=5,
                               chain_rounds=32)
    assert row["block"] == "32x128"
    # Round-5 definition: DIFFERENCED amortized per-round cost (live
    # exchange minus local control) over on-device chains, recorded in
    # the row so readers know what the number means.
    assert row["rounds_per_trial"] == 32
    assert row["timing"] == "amortized-diff-32"
    if row.get("noise_floor"):
        # Legitimate on a loaded host: the tiny 32x128 diff never rose
        # above the clamp; the row must then be an explained null.
        assert row["p50_us"] is None
    else:
        assert row["p50_us"] >= 0
        assert row["p90_us"] is None or row["p90_us"] >= row["p50_us"]


def test_bench_halo_rounds_keep_collectives():
    # Regression guard for the round-5 elision bug: the original chained
    # round was slice(exchange(b)) == b, which XLA cancelled to ZERO
    # collective-permutes — every earlier halo "measurement" timed an
    # empty graph (caught by scripts/halo_cross_check.py).  Compiles the
    # SAME module-scope round builder bench_halo_p50 uses, so a future
    # edit to the real round cannot regress silently: the live round
    # must keep its ppermutes in the compiled loop; the control round
    # must have none.
    import numpy as np

    from parallel_convolution_tpu.parallel.mesh import (
        block_sharding, grid_shape,
    )

    mesh = _mesh((2, 2))
    grid = grid_shape(mesh)
    x = jax.device_put(
        np.zeros((1, 64, 256), np.float32), block_sharding(mesh))

    live = bench.halo_bench_rounds(mesh, grid, 1, 8, True)
    ctl = bench.halo_bench_rounds(mesh, grid, 1, 8, False)
    live_hlo = live.lower(x).compile().as_text()
    ctl_hlo = ctl.lower(x).compile().as_text()
    assert live_hlo.count("collective-permute") > 0, "exchange was elided"
    assert ctl_hlo.count("collective-permute") == 0


def test_bench_halo_p50_refuses_1x1():
    # A 1×1 mesh has no collective; the row must be an explicit sentinel,
    # never a vacuous 0.0 (round-1 regression).
    row = bench.bench_halo_p50((32, 128), r=1, mesh=_mesh((1, 1)), trials=2)
    assert row["p50_us"] is None and row["p90_us"] is None
    assert "no collective" in row["unmeasurable"]


def test_bench_rows_carry_timing_mode():
    row = bench.bench_iterate((32, 128), get_filter("blur3"), 2,
                              mesh=_mesh((1, 1)), reps=1)
    assert row["timing"] in ("slope", "fence")


def test_halo_proxy_subprocess():
    from parallel_convolution_tpu.utils import halo_proxy

    row = halo_proxy.run_in_subprocess(n_devices=4, timeout=600)
    assert row.get("proxy") == "cpu-mesh", row
    assert row["devices"] == 4
    assert row["p50_us"] is None or row["p50_us"] >= 0


def test_bench_oracle_proxy_small():
    row = bench.bench_oracle_proxy((64, 64), iters=1)
    assert row["gpixels_per_s"] > 0
    assert row["impl"] in ("cpp-serial", "numpy-oracle")


def test_wall_returns_median():
    calls = []

    def fn(x):
        calls.append(1)
        return x

    t = bench.wall(fn, jax.numpy.ones((4,)), warmup=1, reps=3)
    assert t >= 0 and len(calls) == 4
