"""Semantics pin: hard-coded digests of the normative oracle outputs.

The oracle IS the spec (SURVEY.md §7 — the reference mount was empty, so
the oracle's behavior was declared normative and every backend is tested
bit-exact against it).  These digests freeze that spec: any change to
padding, tap order, accumulation dtype, rounding, or the fixture generator
fails here loudly instead of silently re-baselining the whole suite.
"""

import hashlib

import numpy as np
import pytest

from parallel_convolution_tpu.utils.jax_compat import IS_MODERN_JAX

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.utils import imageio


def _digest(arr) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


GREY = imageio.generate_test_image(32, 48, "grey", seed=99)
RGB = imageio.generate_test_image(24, 40, "rgb", seed=98)


def test_fixture_generator_pinned():
    assert _digest(GREY) == "314e09a88576d412"
    assert _digest(RGB) == "46ff0356c038d06f"


@pytest.mark.parametrize("name,img,iters,want", [
    ("blur3", GREY, 5, "5e7c3ae9bcdb329e"),
    ("gaussian5", GREY, 3, "2548f5f829eb07c2"),
    ("edge3", GREY, 2, "e2badfcff3a1cfa4"),
    ("blur3", RGB, 4, "d45d8074522ee0b7"),
])
def test_oracle_u8_pinned(name, img, iters, want):
    out = oracle.run_serial_u8(img, filters.get_filter(name), iters)
    assert _digest(out) == want


def test_oracle_periodic_pinned():
    out = oracle.run_serial_u8(GREY, filters.get_filter("blur3"), 5,
                               boundary="periodic")
    assert _digest(out) == "a455b7076e6502cb"


def test_oracle_f32_pinned():
    out = oracle.run_serial_f32(GREY.astype(np.float32),
                                filters.get_filter("jacobi3"), 6)
    assert _digest(out) == "223143e6491f0418"


@pytest.mark.skipif(not IS_MODERN_JAX, reason="float-mode FMA contraction pin holds on the current XLA:CPU; old jaxlib rounds the shifted path differently")
def test_float_mode_fma_contract():
    """Round-5 soak find, pinned: f32 FLOAT-mode chained runs live in the
    rounding regime, where the compiled backends' single-rounding FMA
    accumulation diverges from the oracle's two-rounding mul+add by ulps
    — while staying bit-identical ACROSS backends (one rounding
    discipline) and while quantize mode (the byte-compare contract)
    remains exactly equal because its u8 semantics keep every product
    and partial sum exactly representable.  See DESIGN.md
    "Bit-exactness as an architectural constraint"."""
    import jax

    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.parallel import step

    filt = filters.get_filter("gaussian5")
    img = imageio.generate_test_image(63, 85, "grey", seed=521)
    x = img.astype(np.float32)
    mesh = mesh_lib.make_grid_mesh(jax.devices()[:1], (1, 1))

    want = x.copy()
    for _ in range(3):
        want = oracle.correlate_once(want, filt, "zero")
    got_shifted = np.asarray(step.sharded_iterate(
        x[None], filt, 3, mesh=mesh, quantize=False, backend="shifted"))[0]
    got_pallas = np.asarray(step.sharded_iterate(
        x[None], filt, 3, mesh=mesh, quantize=False, backend="pallas"))[0]

    # Across compiled backends: bit-identical (same rounding discipline).
    np.testing.assert_array_equal(got_shifted, got_pallas)
    # Vs the two-rounding oracle: ulp-level agreement, not byte equality.
    np.testing.assert_allclose(got_shifted, want, rtol=0, atol=1e-3)

    # The byte-compare contract itself is untouched: quantize mode stays
    # exactly equal on the same workload.
    want_u8 = oracle.run_serial_u8(img, filt, 3)
    got_u8 = np.asarray(step.sharded_iterate(
        x[None], filt, 3, mesh=mesh, quantize=True,
        backend="pallas")).astype(np.uint8)[0]
    np.testing.assert_array_equal(got_u8, want_u8)


def test_quantize_nonmargin_gaussian_contract():
    """DESIGN.md precision class 3, pinned: arbitrary-sigma Gaussian taps
    have no integer divisor, so quantize mode carries no rint-margin
    theorem — the contract narrows to cross-backend bit-identity plus at
    most one quantum of deviation from the two-rounding oracle.  (Classes
    1-2 — every registry filter — keep full byte equality; the 400-config
    soak and the whole suite pin that.)"""
    import jax

    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.parallel import step

    filt = filters.gaussian(5, 0.7)
    img = imageio.generate_test_image(96, 128, "grey", seed=0)
    want = oracle.run_serial_u8(img, filt, 5)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    mesh = mesh_lib.make_grid_mesh(jax.devices()[:1], (1, 1))

    outs = {}
    for backend in ("shifted", "pallas"):
        out = step.sharded_iterate(x, filt, 5, mesh=mesh, quantize=True,
                                   backend=backend)
        outs[backend] = imageio.planar_to_interleaved(
            np.asarray(out).astype(np.uint8))

    # One rounding discipline across compiled backends: bit-identical.
    np.testing.assert_array_equal(outs["shifted"], outs["pallas"])
    # Vs the oracle: this config measures a single quantum at isolated
    # pixels (the straddle is real, not hypothetical).  The <=1 here
    # pins THIS config's measured behavior, not a theorem — flipped
    # bytes feed later levels' re-quantization, so no general bound
    # exists; if an XLA change moves this, the pin flags it for
    # re-measurement rather than guaranteeing the old number.
    diff = np.abs(outs["pallas"].astype(int) - want.astype(int))
    assert int(diff.max()) <= 1
