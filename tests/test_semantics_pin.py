"""Semantics pin: hard-coded digests of the normative oracle outputs.

The oracle IS the spec (SURVEY.md §7 — the reference mount was empty, so
the oracle's behavior was declared normative and every backend is tested
bit-exact against it).  These digests freeze that spec: any change to
padding, tap order, accumulation dtype, rounding, or the fixture generator
fails here loudly instead of silently re-baselining the whole suite.
"""

import hashlib

import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.utils import imageio


def _digest(arr) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


GREY = imageio.generate_test_image(32, 48, "grey", seed=99)
RGB = imageio.generate_test_image(24, 40, "rgb", seed=98)


def test_fixture_generator_pinned():
    assert _digest(GREY) == "314e09a88576d412"
    assert _digest(RGB) == "46ff0356c038d06f"


@pytest.mark.parametrize("name,img,iters,want", [
    ("blur3", GREY, 5, "5e7c3ae9bcdb329e"),
    ("gaussian5", GREY, 3, "2548f5f829eb07c2"),
    ("edge3", GREY, 2, "e2badfcff3a1cfa4"),
    ("blur3", RGB, 4, "d45d8074522ee0b7"),
])
def test_oracle_u8_pinned(name, img, iters, want):
    out = oracle.run_serial_u8(img, filters.get_filter(name), iters)
    assert _digest(out) == want


def test_oracle_periodic_pinned():
    out = oracle.run_serial_u8(GREY, filters.get_filter("blur3"), 5,
                               boundary="periodic")
    assert _digest(out) == "a455b7076e6502cb"


def test_oracle_f32_pinned():
    out = oracle.run_serial_f32(GREY.astype(np.float32),
                                filters.get_filter("jacobi3"), 6)
    assert _digest(out) == "223143e6491f0418"
