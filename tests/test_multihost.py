"""Two-OS-process multi-host execution (the reference's multi-node tier).

The reference needs a real cluster for >1 rank; here two actual OS
processes run ``jax.distributed.initialize`` on CPU (4 virtual devices
each), share one 8-device mesh, and execute the sharded step + sharded I/O
+ checkpoint/resume across the process boundary — turning multihost.py's
docstring claims into executed evidence (SURVEY.md §3.2 process boundary,
§5 comm backend).
"""

import json
import os

import socket
import subprocess
import sys
from pathlib import Path

import pytest

from parallel_convolution_tpu.utils.jax_compat import IS_MODERN_JAX

_WORKER = Path(__file__).with_name("_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(not IS_MODERN_JAX, reason="CPU multiprocess collectives unimplemented in old jaxlib")
def test_two_process_distributed(tmp_path):
    from parallel_convolution_tpu.utils.platform import child_env_cpu

    n, port = 2, _free_port()
    repo_root = str(_WORKER.parent.parent)
    env = child_env_cpu(n_devices=4)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))

    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(pid), str(n), str(port),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(n)
    ]
    # Drain both pipes CONCURRENTLY: sequential communicate() can deadlock
    # — worker B blocks on a full stdout pipe while worker A sits in a
    # collective waiting for B, and we sit in communicate(A).
    from concurrent.futures import ThreadPoolExecutor

    try:
        with ThreadPoolExecutor(len(procs)) as pool:
            outs = [f.result() for f in [
                pool.submit(lambda p=p: p.communicate(timeout=540)[0])
                for p in procs]]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"

    result = json.loads((tmp_path / "result.json").read_text())
    assert result["ok"], result
    assert result["process_count"] == 2
    assert result["global_devices"] == 8
    assert result["local_devices"] == 4
    assert result["bitexact_output"] and result["resume_bitexact_local"]
