"""Elastic mesh recovery (round 10): grid-shape-agnostic checkpoint
resharding, shard quarantine with named causes, supervisor reshape legs,
and serve-through-shrink in the serving engine."""

import sys
import threading

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.resilience import elastic, faults
from parallel_convolution_tpu.resilience.retry import RetryPolicy
from parallel_convolution_tpu.resilience.supervisor import Leg, Supervisor
from parallel_convolution_tpu.utils import checkpoint, imageio
from parallel_convolution_tpu.utils import platform as platform_lib


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _prepare(img, m, filt):
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    return step._prepare(x, m, filt.radius)


def _make_snapshots(ckpt_dir, img, m, filt, total=6, every=2, fuse=1):
    """run_checkpointed leaving snapshots at `every` boundaries."""
    xs, valid_hw, _ = _prepare(img, m, filt)
    checkpoint.run_checkpointed(
        xs, filt, total_iters=total, mesh=m, valid_hw=valid_hw,
        ckpt_dir=ckpt_dir, every=every, fuse=fuse)
    return valid_hw


# ------------------------------------------------ checkpoint resharding
@pytest.mark.parametrize("target", [(1, 2), (2, 2), (1, 1), (4, 2)])
def test_reshard_resume_bitexact(tmp_path, grey_odd, target):
    """The acceptance property: a snapshot written on the 2x4 mesh
    resumes byte-identically (vs the single-device oracle) on shrunken
    AND re-gridded meshes, with a fused (mid-`fuse`) iteration count —
    snapshots land at 3 and 6 with fuse=2, so the resumed run continues
    from a chunk boundary that is not a fuse multiple."""
    filt = filters.get_filter("blur3")
    total, every, fuse = 11, 3, 2
    ck = tmp_path / "ck"
    _make_snapshots(ck, grey_odd, _mesh((2, 4)), filt, total=8, every=every,
                    fuse=fuse)
    assert checkpoint.load_meta(ck)["iters_done"] == 6
    tmesh = _mesh(target)
    xs, valid_hw, _ = _prepare(grey_odd, tmesh, filt)
    with pytest.warns(checkpoint.CheckpointWarning, match="resharding"):
        out = checkpoint.run_checkpointed(
            xs, filt, total_iters=total, mesh=tmesh, valid_hw=valid_hw,
            ckpt_dir=ck, every=every, fuse=fuse)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    want = oracle.run_serial_u8(grey_odd, filt, total)
    np.testing.assert_array_equal(got[0], want)


def test_reshard_load_state_bytes_equal(tmp_path, rgb_odd):
    """load_state onto a different grid returns the same global pixels
    as loading onto the grid that wrote it (RGB + odd dims: the pad rim
    really changes between the grids)."""
    filt = filters.get_filter("gaussian5")
    src = _mesh((2, 2))
    ck = tmp_path / "ck"
    valid_hw = _make_snapshots(ck, rgb_odd, src, filt, total=4, every=2)
    same, meta_same = checkpoint.load_state(ck, src)
    with pytest.warns(checkpoint.CheckpointWarning, match="resharding"):
        other, meta_other = checkpoint.load_state(ck, _mesh((1, 2)))
    assert "resharded_from" not in meta_same
    assert meta_other["resharded_from"] == [2, 2]
    assert meta_other["iters_done"] == meta_same["iters_done"]
    H, W = valid_hw
    np.testing.assert_array_equal(np.asarray(same)[:, :H, :W],
                                  np.asarray(other)[:, :H, :W])


# ------------------------------------------------ quarantine diagnosis
@pytest.mark.parametrize("damage,cause", [
    ("missing", "missing shard_1_0.npy"),
    ("bitflip", "checksum mismatch in shard_1_0.npy"),
    ("truncate", "truncated shard_1_0.npy"),
    ("meta", "unreadable meta"),
])
def test_quarantine_warning_names_snapshot_shard_and_cause(
        tmp_path, grey_odd, damage, cause):
    filt = filters.get_filter("blur3")
    ck = tmp_path / "ck"
    _make_snapshots(ck, grey_odd, _mesh((2, 2)), filt, total=6, every=2)
    latest = ck / (ck / "LATEST").read_text().strip()
    victim = latest / "shard_1_0.npy"
    if damage == "missing":
        victim.unlink()
    elif damage == "bitflip":
        raw = bytearray(victim.read_bytes())
        raw[-1] ^= 0xFF
        victim.write_bytes(bytes(raw))
    elif damage == "truncate":
        victim.write_bytes(victim.read_bytes()[:-8])
    else:
        (latest / "meta.json").write_text("{not json")
    with pytest.raises(checkpoint.CheckpointCorrupt) as ei:
        checkpoint.load_state(ck, _mesh((2, 2)))
    assert cause in str(ei.value) and latest.name in str(ei.value)
    # fallback quarantines ONLY that snapshot; the warning carries the
    # snapshot name and the per-shard cause for triage.
    with pytest.warns(checkpoint.CheckpointWarning) as rec:
        _, meta = checkpoint.load_state(ck, _mesh((2, 2)), fallback=True)
    text = "".join(str(w.message) for w in rec)
    assert cause in text and latest.name in text
    assert meta["iters_done"] == 2  # snapshots were at 2 and 4


def test_io_read_fault_quarantines_and_resume_is_bitexact(tmp_path,
                                                          grey_odd):
    """Acceptance: an injected io_read fault during shard validation
    quarantines only the newest snapshot (named cause) and recovery
    reshards from the next valid one, byte-identical to the oracle."""
    filt = filters.get_filter("blur3")
    total, every = 9, 2
    ck = tmp_path / "ck"
    _make_snapshots(ck, grey_odd, _mesh((2, 4)), filt, total=total,
                    every=every)
    latest = (ck / "LATEST").read_text().strip()
    tmesh = _mesh((2, 2))
    xs, valid_hw, _ = _prepare(grey_odd, tmesh, filt)
    with faults.injected("io_read:1") as plan:
        with pytest.warns(checkpoint.CheckpointWarning) as rec:
            out = checkpoint.run_checkpointed(
                xs, filt, total_iters=total, mesh=tmesh, valid_hw=valid_hw,
                ckpt_dir=ck, every=every)
        assert plan.fired
    text = "".join(str(w.message) for w in rec)
    assert latest in text and "unreadable shard_0_0.npy" in text
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    np.testing.assert_array_equal(
        got[0], oracle.run_serial_u8(grey_odd, filt, total))


# ------------------------------------- prune-vs-reader/writer races
def test_candidate_walk_survives_vanished_snapshot(tmp_path, grey_odd,
                                                   monkeypatch):
    """The prune-vs-read race: a snapshot listed by _candidate_snaps but
    pruned before its meta is read must quarantine (torn meta), not
    crash the recovery walk."""
    filt = filters.get_filter("blur3")
    ck = tmp_path / "ck"
    _make_snapshots(ck, grey_odd, _mesh((2, 2)), filt, total=6, every=2)
    real = checkpoint._candidate_snaps(ck)
    ghost = ck / "it_99999999"  # pruned between listing and meta read
    monkeypatch.setattr(checkpoint, "_candidate_snaps",
                        lambda d: [ghost] + real)
    with pytest.warns(checkpoint.CheckpointWarning, match="unreadable meta"):
        _, meta = checkpoint.load_state(ck, _mesh((2, 2)), fallback=True)
    assert meta["iters_done"] == 4


def test_concurrent_writer_prune_vs_reader(tmp_path, grey_small):
    """A writer snapshotting (and pruning) while a reader walks the
    candidate list: the prune-vs-read race round 7 only covered via the
    torn-LATEST case.  The reader may see a quarantined (vanishing)
    snapshot — a typed CheckpointCorrupt, absorbed by fallback — but
    never a raw OSError from a dir pruned mid-walk, and the final state
    must load cleanly."""
    import warnings

    filt = filters.get_filter("blur3")
    m = _mesh((1, 1))
    xs, valid_hw, _ = _prepare(grey_small, m, filt)
    ck = tmp_path / "ck"
    base = {"valid_hw": list(valid_hw), "grid": [1, 1],
            "shape": list(xs.shape)}
    errors, stop = [], threading.Event()

    def reader():
        while not stop.is_set():
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    checkpoint.load_state(ck, m, fallback=True)
                checkpoint._candidate_snaps(ck)
            except FileNotFoundError:
                pass  # nothing written yet
            except checkpoint.CheckpointCorrupt:
                pass  # every candidate vanished mid-walk: typed, retryable
            except Exception as e:  # noqa: BLE001 — the hardening target
                errors.append(repr(e))
                return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for it in range(1, 15):
            checkpoint.save_state(ck, xs, dict(base, iters_done=it))
    finally:
        stop.set()
        t.join(60)
    assert not errors
    arr, meta = checkpoint.load_state(ck, m, fallback=True)
    assert meta["iters_done"] == 14
    np.testing.assert_array_equal(np.asarray(arr), np.asarray(xs))
    names = [p.name for p in checkpoint._candidate_snaps(ck)]
    assert names[0] == "it_00000014" and len(names) == checkpoint.KEEP_SNAPSHOTS


# ------------------------------------------------ elastic primitives
def test_grid_ladder_and_next_fit():
    assert elastic.grid_ladder((2, 4)) == ["2x4", "2x2", "2x1", "1x1"]
    assert elastic.grid_ladder((1, 1)) == ["1x1"]
    ladder = elastic.grid_ladder((2, 4))
    assert elastic.next_fit(ladder, 1, live=2) == 2      # 2x1 fits 2
    assert elastic.next_fit(ladder, 1, live=None) == 1   # unknown: one rung
    assert elastic.next_fit(ladder, 1, live=0) == 3      # nothing fits: last
    assert elastic.next_fit(ladder, 99, live=8) == 3     # clamped


def test_probe_device_count_sim_override(monkeypatch):
    monkeypatch.setenv(platform_lib.SIM_DEVICES_ENV, "3")
    assert platform_lib.probe_device_count() == 3


def test_detect_change_proposes_fitting_spec(monkeypatch):
    m = _mesh((2, 4))
    monkeypatch.setenv(platform_lib.SIM_DEVICES_ENV, "8")
    assert elastic.detect_change(m) is None  # nothing lost
    monkeypatch.setenv(platform_lib.SIM_DEVICES_ENV, "4")
    ch = elastic.detect_change(m)
    assert ch.lost == 4 and ch.new_spec == "2x2"
    monkeypatch.setenv(platform_lib.SIM_DEVICES_ENV, "0")
    assert elastic.detect_change(m).new_spec is None


def test_reshape_mesh_builds_and_validates():
    m = elastic.reshape_mesh("1x2")
    assert mesh_lib.grid_shape(m) == (1, 2)
    with pytest.raises(ValueError, match="devices"):
        elastic.reshape_mesh((99, 99))


# ------------------------------------------------ supervisor reshape leg
def test_supervisor_reshape_leg_walks_mesh_ladder(tmp_path, monkeypatch):
    """A leg that dies with a device-loss signature on grids bigger than
    the (simulated) live-device count walks its mesh ladder — skipping
    rungs that cannot fit — and completes on the one that does."""
    monkeypatch.setenv(platform_lib.SIM_DEVICES_ENV, "2")
    done = tmp_path / "out.json"
    script = (
        "import os, sys, pathlib\n"
        "m = os.environ.get('PCTPU_MESH', '')\n"
        "if m != '1x2':\n"
        "    print('DEVICE LOST on mesh ' + m, file=sys.stderr)\n"
        "    sys.exit(1)\n"
        f"pathlib.Path({str(done)!r}).write_text('served on ' + m)\n"
    )
    leg = Leg(name="reshapey", cmd=[sys.executable, "-c", script],
              done_file=str(done), meshes=["2x4", "2x2", "1x2"],
              reshape_pattern="DEVICE LOST")
    sup = Supervisor([leg], tmp_path,
                     policy=RetryPolicy(max_attempts=3, base_delay=0.01,
                                        max_delay=0.01),
                     sleep=lambda d: None, log=lambda m: None)
    assert sup.run() == 0
    st = sup._status["legs"]["reshapey"]
    # live=2: the probe skips 2x2 (needs 4) straight to 1x2.
    assert st["mesh"] == "1x2" and st["reshapes"] == 1
    assert st["attempts"] == 2
    assert done.read_text() == "served on 1x2"


def test_leg_validation_rejects_bad_reshape_config():
    with pytest.raises(ValueError, match="meshes ladder"):
        Leg.from_dict({"name": "x", "cmd": ["true"],
                       "reshape_pattern": "boom"})
    with pytest.raises(ValueError, match="RxC"):
        Leg.from_dict({"name": "x", "cmd": ["true"],
                       "meshes": ["2x4", "nope"]})


# ------------------------------------------------ mesh swap in the stack
def test_reshard_prepared_matches_prepare(grey_odd):
    filt = filters.get_filter("blur3")
    src, dst = _mesh((2, 4)), _mesh((1, 2))
    xs, valid_hw, _ = _prepare(grey_odd, src, filt)
    moved = step.reshard_prepared(xs, valid_hw, dst)
    fresh, _, _ = _prepare(grey_odd, dst, filt)
    assert moved.shape == fresh.shape
    np.testing.assert_array_equal(np.asarray(moved), np.asarray(fresh))


def test_model_set_mesh_bitexact(grey_small):
    from parallel_convolution_tpu.models import ConvolutionModel

    model = ConvolutionModel(filt="blur3", mesh=_mesh((2, 4)))
    a = model.run_image(grey_small, 3)
    model.set_mesh("1x2")
    assert mesh_lib.grid_shape(model.mesh) == (1, 2)
    b = model.run_image(grey_small, 3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(
        b, oracle.run_serial_u8(grey_small, filters.get_filter("blur3"), 3))


# ------------------------------------------------ serve-through-shrink
def test_service_reshape_serves_through_shrink(grey_small):
    """Acceptance: the serving engine survives a mesh shrink without a
    process restart — in-flight requests drain and complete on the old
    grid, the executable cache re-warms on the new one, and every
    response stamps the grid that produced its bytes."""
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request, Response,
    )

    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_small, filt, 2)
    svc = ConvolutionService(_mesh((2, 4)), max_delay_s=0.05, max_batch=4)
    try:
        def req():
            return Request(image=grey_small, filter_name="blur3", iters=2)

        first = svc.submit(req())
        assert isinstance(first, Response)
        assert first.effective_grid == "2x4"
        np.testing.assert_array_equal(first.image, want)
        # In-flight at reshape time: enqueued, not yet executed — the
        # drain must complete them on the OLD grid.
        slots = [svc.submit(req(), wait=False) for _ in range(3)]
        info = svc.reshape("1x2")
        assert info["old_grid"] == (2, 4) and info["grid"] == (1, 2)
        assert info["rewarmed"] == 1 and info["skipped"] == 0
        for s in slots:
            r = s.result(60)
            assert isinstance(r, Response) and r.effective_grid == "2x4"
            np.testing.assert_array_equal(r.image, want)
        # Post-shrink requests ride the re-warmed executable: the compile
        # counter must not move.
        compiles = svc.engine.stats["compiles"]
        after = svc.submit(req())
        assert isinstance(after, Response)
        assert after.effective_grid == "1x2"
        np.testing.assert_array_equal(after.image, want)
        assert svc.engine.stats["compiles"] == compiles
        assert svc.engine.stats["reshapes"] == 1
        assert svc.stats["reshapes"] == 1
        snap = svc.snapshot()
        assert snap["mesh"] == "1x2"
        assert len(snap["resident"]) == 1  # the re-warmed key survived
    finally:
        svc.close()


def test_engine_reshape_skips_unfittable_keys_and_guards_stale(grey_small):
    from parallel_convolution_tpu.serving.engine import WarmEngine

    eng = WarmEngine(_mesh((1, 2)), fallback=False)
    # 3x40 gaussian5 (radius 2): fine on 1x2 (block rows 3 >= 2), no
    # home on 4x2 (block rows 1 < radius) — must be skipped, not fatal.
    key_small = eng.key_for((1, 3, 40), filter_name="gaussian5", iters=2)
    key_ok = eng.key_for((1, 24, 36), filter_name="blur3", iters=2)
    imgs = (imageio.interleaved_to_planar(grey_small)
            .astype(np.float32)[None])
    eng.run_batch(key_ok, imgs)
    eng.entry(key_small)
    with pytest.warns(UserWarning, match="no home"):
        info = eng.reshape(_mesh((4, 2)))
    assert info["rewarmed"] >= 1 and info["skipped"] == 1
    with pytest.raises(ValueError, match="stale"):
        eng.run_batch(key_ok, imgs)  # old-grid key after the swap
