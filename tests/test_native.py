"""Native C++ tier vs the oracle (built on demand; skipped without g++)."""

import shutil

import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.utils import imageio

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="session")
def native():
    from parallel_convolution_tpu import native as native_pkg
    from parallel_convolution_tpu.native import serial_native

    native_pkg.load()
    return serial_native


@pytest.mark.parametrize("mode", ["grey", "rgb"])
@pytest.mark.parametrize("name", ["blur3", "gaussian5", "edge5", "sharpen3"])
def test_native_serial_bitexact(native, mode, name):
    img = imageio.generate_test_image(33, 47, mode, seed=21)
    f = filters.get_filter(name)
    got = native.run_serial_u8(img, f, 4)
    want = oracle.run_serial_u8(img, f, 4)
    np.testing.assert_array_equal(got, want)


def test_native_zero_iters(native, grey_small):
    f = filters.get_filter("blur3")
    np.testing.assert_array_equal(
        native.run_serial_u8(grey_small, f, 0), grey_small
    )


@pytest.mark.parametrize("iters", [1, 2, 3, 6])
def test_native_double_buffer_parity(native, grey_small, iters):
    # Exercises the even/odd buffer-swap routing.
    f = filters.get_filter("blur3")
    got = native.run_serial_u8(grey_small, f, iters)
    want = oracle.run_serial_u8(grey_small, f, iters)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mode", ["grey", "rgb"])
def test_native_block_io(native, tmp_path, mode):
    img = imageio.generate_test_image(20, 28, mode, seed=22)
    p = str(tmp_path / "img.raw")
    imageio.write_raw(p, img)
    blk = native.read_block(p, 20, 28, mode, 3, 15, 5, 21)
    np.testing.assert_array_equal(blk, img[3:15, 5:21])

    q = str(tmp_path / "out.raw")
    imageio.allocate_raw(q, 20, 28, mode)
    for bi in range(2):
        r0, r1 = imageio.block_bounds(20, 2, bi)
        native.write_block(q, 20, 28, mode, r0, 0, img[r0:r1])
    np.testing.assert_array_equal(imageio.read_raw(q, 20, 28, mode), img)


def test_native_block_io_bounds_error(native, tmp_path):
    p = str(tmp_path / "img.raw")
    imageio.write_raw(p, np.zeros((4, 4), np.uint8))
    with pytest.raises(OSError):
        native.read_block(p, 4, 4, "grey", 0, 5, 0, 4)


def test_native_layout_roundtrip(native):
    img = imageio.generate_test_image(12, 18, "rgb", seed=23)
    pl = native.interleaved_to_planar(img)
    np.testing.assert_array_equal(pl, imageio.interleaved_to_planar(img))
    np.testing.assert_array_equal(native.planar_to_interleaved(pl), img)
