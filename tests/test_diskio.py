"""Guarded disk IO + durability degrade ladders (round 24).

The ISSUE 20 acceptance properties, unit-sized:

* mode grammar — ``PCTPU_DISK_MODES`` specs validate site AND mode, so
  a typo'd drill can't silently never fire; dict installs re-validate;
* guard semantics — ``enospc``/``eio`` raise their ``OSError`` before
  any byte lands; ``torn_write`` through :func:`guarded_write` lands a
  flushed PREFIX then raises (the bytes a power loss leaves behind);
  ``slow_write`` stalls then succeeds; a triggered site with NO
  installed mode re-raises the raw ``InjectedFault`` so every
  pre-round-24 drill keeps its exact semantics;
* WAL degrade ladder — sustained append failure flips the router into
  a ``durability: degraded`` window that keeps serving (stamped on
  every response); the first healthy append re-arms with a fresh
  compaction snapshot, and a takeover replay after the degraded window
  resurrects nothing stale;
* events ladder — ``events.emit`` under ENOSPC counts dropped lines
  instead of raising into whatever the caller was doing.
"""

from __future__ import annotations

import base64
import errno
import io
import time

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.obs.events import EventLog
from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.resilience import diskio, faults
from parallel_convolution_tpu.resilience.faults import InjectedFault
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.serving.router import (
    InProcessReplica, ReplicaRouter, TenantQuotas,
)
from parallel_convolution_tpu.serving.service import ConvolutionService
from parallel_convolution_tpu.utils import imageio


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    diskio.uninstall_modes()


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _factory(shape=(1, 2), **kw):
    kw.setdefault("max_delay_s", 0.002)

    def make():
        return ConvolutionService(_mesh(shape), **kw)

    return make


def _batch_body(img, rid, tenant="t"):
    return {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "blur3", "iters": 1, "request_id": rid,
        "tenant": tenant}


def _converge_body(img, rid, tenant="t"):
    return {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "jacobi3", "backend": "shifted", "quantize": False,
        "tol": 0.0, "max_iters": 40, "check_every": 10,
        "request_id": rid, "tenant": tenant}


def _wal_router(reps, wal_path, **kw):
    kw.setdefault("start_health", False)
    kw.setdefault("breaker_cooldown_s", 0.2)
    return ReplicaRouter(
        reps, wal=str(wal_path),
        quotas=TenantQuotas(rate=1.0, burst=1e6, clock=lambda: 0.0),
        pricer=WorkPricer(min_units=1e-9), **kw)


# ------------------------------------------------------- mode grammar


def test_modes_from_spec_parses_and_rejects():
    modes = diskio.modes_from_spec(
        "wal_write=torn_write, cache_spill=enospc")
    assert modes == {"wal_write": "torn_write", "cache_spill": "enospc"}
    assert diskio.modes_from_spec("") == {}
    with pytest.raises(ValueError, match="unknown disk site"):
        diskio.modes_from_spec("wal_wrte=enospc")
    with pytest.raises(ValueError, match="unknown disk mode"):
        diskio.modes_from_spec("wal_write=slow")
    with pytest.raises(ValueError, match="expected site=mode"):
        diskio.modes_from_spec("wal_write")
    # torn_write only where a partial payload can actually land.
    with pytest.raises(ValueError, match="unknown disk mode"):
        diskio.modes_from_spec("wal_fsync=torn_write")
    with pytest.raises(ValueError, match="unknown disk mode"):
        diskio.modes_from_spec("cache_promote=enospc")


def test_install_modes_validates_dict_and_spec():
    with pytest.raises(ValueError, match="unknown disk site/mode"):
        diskio.install_modes({"wal_write": "nope"})
    with pytest.raises(ValueError, match="unknown disk site/mode"):
        diskio.install_modes({"nope": "eio"})
    diskio.install_modes("events_emit=eio")
    assert diskio.installed_modes() == {"events_emit": "eio"}
    diskio.install_modes(None)
    assert diskio.installed_modes() == {}
    assert diskio.modes_from_env(
        {"PCTPU_DISK_MODES": "evidence_write=eio"}) == {
            "evidence_write": "eio"}
    assert diskio.modes_from_env({}) == {}


def test_disk_sites_are_registered_fault_sites():
    assert set(diskio.DISK_SITES) <= set(faults.SITE_TABLE)


# ---------------------------------------------------- guard semantics


def test_consult_translates_each_mode():
    diskio.install_modes({"wal_write": "enospc"})
    with faults.injected("wal_write:*"):
        with pytest.raises(OSError) as e:
            diskio.consult("wal_write")
        assert e.value.errno == errno.ENOSPC
    diskio.install_modes({"wal_write": "eio"})
    with faults.injected("wal_write:*"):
        with pytest.raises(OSError) as e:
            diskio.consult("wal_write")
        assert e.value.errno == errno.EIO
    # A torn READ surface can't half-succeed: plain consult raises EIO.
    diskio.install_modes({"wal_write": "torn_write"})
    with faults.injected("wal_write:*"):
        with pytest.raises(OSError) as e:
            diskio.consult("wal_write")
        assert e.value.errno == errno.EIO
    diskio.install_modes({"wal_write": "slow_write"})
    with faults.injected("wal_write:*"):
        t0 = time.monotonic()
        diskio.consult("wal_write")           # stalls, then returns
        assert time.monotonic() - t0 >= diskio.SLOW_WRITE_S * 0.8
    # No plan installed: the guard is a no-op.
    diskio.consult("wal_write")


def test_deferred_consult_hands_torn_to_the_caller():
    diskio.install_modes({"cache_spill": "torn_write"})
    with faults.injected("cache_spill:*"):
        assert diskio.deferred_consult("cache_spill") == "torn_write"
    diskio.install_modes({"cache_spill": "enospc"})
    with faults.injected("cache_spill:*"):
        with pytest.raises(OSError) as e:
            diskio.deferred_consult("cache_spill")
        assert e.value.errno == errno.ENOSPC
    assert diskio.deferred_consult("cache_spill") is None


def test_guarded_write_torn_lands_flushed_prefix_then_raises():
    diskio.install_modes({"wal_write": "torn_write"})
    buf = io.BytesIO()
    payload = b"x" * 100
    with faults.injected("wal_write:1"):
        with pytest.raises(OSError, match="torn write"):
            diskio.guarded_write("wal_write", buf, payload)
    # Exactly the prefix a power loss leaves behind — half the payload.
    assert buf.getvalue() == payload[:50]
    # Subsequent (un-triggered) writes pass through whole.
    n = diskio.guarded_write("wal_write", buf, b"yz")
    assert n == 2 and buf.getvalue() == payload[:50] + b"yz"


def test_guarded_replace_torn_is_metadata_eio_src_stays(tmp_path):
    src, dst = tmp_path / "a", tmp_path / "b"
    src.write_bytes(b"payload")
    diskio.install_modes({"evidence_write": "torn_write"})
    with faults.injected("evidence_write:1"):
        with pytest.raises(OSError) as e:
            diskio.guarded_replace("evidence_write", src, dst)
    # rename is atomic: no half-state, the src file simply stays.
    assert e.value.errno == errno.EIO
    assert src.exists() and not dst.exists()
    diskio.guarded_replace("evidence_write", src, dst)
    assert dst.read_bytes() == b"payload" and not src.exists()


def test_triggered_site_without_mode_reraises_raw_fault():
    """Pre-round-24 drills keep their exact semantics: no installed
    mode means the raw InjectedFault, not a translated OSError."""
    diskio.uninstall_modes()
    with faults.injected("wal_write:1"):
        with pytest.raises(InjectedFault):
            diskio.consult("wal_write")
    buf = io.BytesIO()
    with faults.injected("wal_write:1"):
        with pytest.raises(InjectedFault):
            diskio.guarded_write("wal_write", buf, b"data")
    assert buf.getvalue() == b""          # nothing landed


def test_injected_counts_track_translated_faults():
    before = diskio.injected_counts().get("wal_fsync=eio", 0)
    diskio.install_modes({"wal_fsync": "eio"})
    with faults.injected("wal_fsync:*"):
        for _ in range(3):
            with pytest.raises(OSError):
                diskio.consult("wal_fsync")
    assert diskio.injected_counts()["wal_fsync=eio"] - before == 3


# ------------------------------------------------ events degrade ladder


def test_events_emit_enospc_counts_dropped_never_raises(tmp_path):
    log = EventLog(tmp_path / "events.ndjson")
    diskio.install_modes({"events_emit": "enospc"})
    try:
        with faults.injected("events_emit:2+"):
            for i in range(4):
                log.emit("chaos", n=i)    # never raises into the caller
    finally:
        log.close()
    assert log.dropped == 3
    lines = (tmp_path / "events.ndjson").read_text().splitlines()
    written = [ln for ln in lines if '"chaos"' in ln]
    # The full-disk ledger balances: written + dropped == emitted.
    assert len(written) + log.dropped == 4


# --------------------------------------------- WAL durability ladder


def test_wal_degrade_window_rearm_and_clean_replay(tmp_path):
    """The ENOSPC drill, unit-sized: sustained append failure flips
    ``durability: degraded`` but serving continues byte-correct; the
    first healthy append re-arms with a compaction snapshot; a takeover
    replay after the window carries the finalized id and resurrects no
    stale live jobs."""
    img = _img()
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)
    reps = [InProcessReplica(_factory(), name=f"g{i}") for i in range(2)]
    wal_path = tmp_path / "r.wal"
    r1 = _wal_router(reps, wal_path)
    diskio.install_modes({"wal_write": "enospc"})
    stamps = []
    try:
        with faults.injected("wal_write:1+"):
            for i in range(4):
                st, wire = r1.request(_batch_body(img, f"b{i}"))
                assert st == 200 and wire["ok"], wire
                got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                    np.uint8).reshape(img.shape)
                assert np.array_equal(got, want)   # degraded ≠ wrong
                stamps.append(wire["router"]["durability"])
            # A converge finishing INSIDE the window: its final must
            # survive the later replay even though no append landed.
            st, rows = r1.converge(_converge_body(img, "cv-deg"))
            rows = list(rows)
            assert rows[-1]["kind"] == "final"
            assert rows[-1]["router"]["durability"] == "degraded"
        assert stamps[0] == "ok" and stamps[-1] == "degraded"
        assert r1.stats["wal_degraded_windows"] == 1
        assert r1.snapshot()["durability"] == "degraded"
        # Heal: the very response whose append succeeded stamps ok.
        diskio.uninstall_modes()
        st, wire = r1.request(_batch_body(img, "heal"))
        assert wire["router"]["durability"] == "ok"
        assert r1.stats["wal_rearms"] == 1
        assert r1.snapshot()["durability"] == "ok"
    finally:
        r1.close(close_replicas=False)
    # Takeover replay: the re-armed snapshot is the truth on disk.
    r2 = _wal_router(reps, wal_path)
    try:
        live, finalized = r2.jobs.export()
        assert any(k.endswith("cv-deg") for k in finalized)
        assert not live                   # nothing stale came back
        # And the recovered plane still serves the degraded-window
        # request's bytes fresh (exactly-once: dup final refused
        # upstream, recompute is byte-identical).
        st, wire = r2.request(_batch_body(img, "post"))
        got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                            np.uint8).reshape(img.shape)
        assert np.array_equal(got, want)
    finally:
        r2.close()
