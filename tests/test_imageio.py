import numpy as np
import pytest

from parallel_convolution_tpu.utils import imageio


@pytest.mark.parametrize("mode,shape", [("grey", (10, 14)), ("rgb", (10, 14, 3))])
def test_roundtrip(tmp_path, mode, shape):
    img = imageio.generate_test_image(10, 14, mode, seed=7)
    assert img.shape == shape
    p = tmp_path / "img.raw"
    imageio.write_raw(p, img)
    assert p.stat().st_size == img.size
    back = imageio.read_raw(p, 10, 14, mode)
    np.testing.assert_array_equal(back, img)


def test_size_mismatch_raises(tmp_path):
    p = tmp_path / "img.raw"
    p.write_bytes(b"\x00" * 99)
    with pytest.raises(ValueError, match="expected"):
        imageio.read_raw(p, 10, 10, "grey")


def test_bad_mode():
    with pytest.raises(ValueError, match="grey"):
        imageio.image_shape(4, 4, "cmyk")


@pytest.mark.parametrize("mode", ["grey", "rgb"])
def test_block_io_matches_whole(tmp_path, mode):
    img = imageio.generate_test_image(16, 24, mode, seed=8)
    p = tmp_path / "img.raw"
    imageio.write_raw(p, img)
    blk = imageio.read_block(p, 16, 24, mode, 4, 12, 6, 18)
    np.testing.assert_array_equal(blk, img[4:12, 6:18])

    # scatter-write the image block-wise into a fresh file, reassemble
    q = tmp_path / "out.raw"
    imageio.allocate_raw(q, 16, 24, mode)
    for bi in range(2):
        for bj in range(3):
            r0, r1 = imageio.block_bounds(16, 2, bi)
            c0, c1 = imageio.block_bounds(24, 3, bj)
            imageio.write_block(q, 16, 24, mode, r0, c0, img[r0:r1, c0:c1])
    np.testing.assert_array_equal(imageio.read_raw(q, 16, 24, mode), img)


def test_block_bounds_non_divisible():
    # 10 split 3 ways -> 4,3,3 ; covers the non-divisible-dims requirement
    bounds = [imageio.block_bounds(10, 3, i) for i in range(3)]
    assert bounds == [(0, 4), (4, 7), (7, 10)]
    with pytest.raises(IndexError):
        imageio.block_bounds(10, 3, 3)


def test_planar_roundtrip():
    img = imageio.generate_test_image(6, 8, "rgb", seed=9)
    pl = imageio.interleaved_to_planar(img)
    assert pl.shape == (3, 6, 8)
    np.testing.assert_array_equal(imageio.planar_to_interleaved(pl), img)
    g = imageio.generate_test_image(6, 8, "grey", seed=9)
    gp = imageio.interleaved_to_planar(g)
    assert gp.shape == (1, 6, 8)
    np.testing.assert_array_equal(imageio.planar_to_interleaved(gp), g)
