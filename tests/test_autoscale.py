"""Fleet autoscaling (round 17): control loop, pricing, warm placement.

The ISSUE-12 acceptance properties on the 8-virtual-device CPU mesh:

* scale-up/down hysteresis walks deterministically under an injected
  clock (streaks, dead band, cooldown, min/max clamps);
* a joining replica PRE-WARMS its ring shard before its vnodes enter
  the ring, and the shard's per-key compile ledger stays flat through
  the remapped traffic that follows;
* work-unit pricing math: predicted device-seconds scale with pixels
  and iterations, converge jobs price their work budget, the floor and
  the cache behave, and the jax-free multigrid mirror tracks the real
  solver's schedule constants;
* cost-priced token buckets: debt semantics for bigger-than-burst jobs,
  priced charge/refund, and greedy-tenant isolation — a polite tenant's
  p99 stays bounded while one admitted multigrid job runs and the rest
  are priced out;
* the router exposes the autoscaler's own inputs (per-replica
  in-flight, queue depth, warm-key count) via /stats;
* perf_gate gates latency rows (a synthetic 2× p99 regression fails)
  and keys multi-host rows separately.
"""

from __future__ import annotations

import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.serving.autoscaler import AutoScaler
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.serving.router import (
    InProcessReplica, ReplicaRouter, TenantQuotas, TokenBucket, route_key,
)
from parallel_convolution_tpu.serving.service import ConvolutionService
from parallel_convolution_tpu.tuning import costmodel
from parallel_convolution_tpu.utils import imageio

SCRIPTS = Path(__file__).resolve().parent.parent / "scripts"


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey"}
    body.update(kw)
    return body


def _factory(shape=(1, 2), **kw):
    kw.setdefault("max_delay_s", 0.002)
    kw.setdefault("max_batch", 1)

    def make():
        return ConvolutionService(_mesh(shape), **kw)

    return make


class _StubRouter:
    """decide()-only scaffolding: the decision never touches the pool."""


def _scaler(clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_ticks", 2)
    kw.setdefault("down_ticks", 3)
    kw.setdefault("cooldown_s", 10.0)
    return AutoScaler(_StubRouter(), None, clock=clock, **kw)


def _sig(pressure, replicas=2, p99_ms=None):
    return {"replicas": replicas, "live": replicas, "in_flight": 0,
            "queue_depth": 0, "queue_bound": 64, "pressure": pressure,
            "degraded": 0, "p99_ms": p99_ms}


# ------------------------------------------------- hysteresis (injected clock)


def test_scale_up_needs_consecutive_over_pressure_ticks():
    clock = [0.0]
    sc = _scaler(lambda: clock[0], up_pressure=0.5)
    assert sc.decide(_sig(0.9)).action == "hold"     # streak 1 < up_ticks
    assert sc.decide(_sig(0.9)).action == "up"       # streak 2
    # A mixed (dead-band) tick resets the streak: two MORE over-pressure
    # ticks are needed, not one.
    sc2 = _scaler(lambda: clock[0], up_pressure=0.5)
    assert sc2.decide(_sig(0.9)).action == "hold"
    assert sc2.decide(_sig(0.3)).action == "hold"    # dead band: reset
    assert sc2.decide(_sig(0.9)).action == "hold"    # streak back to 1
    assert sc2.decide(_sig(0.9)).action == "up"


def test_scale_down_needs_longer_streak_and_floor():
    clock = [0.0]
    sc = _scaler(lambda: clock[0], down_pressure=0.1, down_ticks=3)
    assert sc.decide(_sig(0.0)).action == "hold"
    assert sc.decide(_sig(0.0)).action == "hold"
    assert sc.decide(_sig(0.0)).action == "down"     # 3rd idle tick
    # At the min-replica floor the same streak holds instead.
    sc2 = _scaler(lambda: clock[0], down_pressure=0.1, down_ticks=1)
    assert sc2.decide(_sig(0.0, replicas=1)).action == "hold"


def test_cooldown_blocks_actions_until_it_elapses():
    clock = [0.0]
    sc = _scaler(lambda: clock[0], up_pressure=0.5, cooldown_s=10.0)
    sc._last_change = 0.0
    clock[0] = 5.0       # mid-cooldown: over-pressure must hold
    assert sc.decide(_sig(0.9)).action == "hold"
    assert sc.decide(_sig(0.9)).reason == "cooldown"
    clock[0] = 11.0      # past cooldown: the accumulated streak fires
    assert sc.decide(_sig(0.9)).action == "up"


def test_max_replicas_clamps_scale_up():
    clock = [0.0]
    sc = _scaler(lambda: clock[0], up_pressure=0.5, max_replicas=2)
    assert sc.decide(_sig(0.9, replicas=2)).action == "hold"
    assert sc.decide(_sig(0.9, replicas=2)).action == "hold"


def test_windowed_p99_is_tick_delta_not_lifetime():
    """The p99 signal must read only THIS tick's new samples: a pile of
    ancient fast samples must not numb it, and a pile of ancient slow
    samples must not pin it high after latencies recover."""
    from parallel_convolution_tpu.obs import metrics as obs_metrics

    hist = obs_metrics.histogram(
        "pctpu_request_phase_seconds",
        "per-request serving latency by phase", ("phase", "backend"))
    clock = [0.0]
    sc = _scaler(lambda: clock[0])
    for _ in range(1000):          # ancient fast history
        hist.observe(0.001, phase="total", backend="shifted")
    assert sc._windowed_p99_ms() is None       # first sight: no window
    for _ in range(10):            # the overload arrives THIS tick
        hist.observe(2.0, phase="total", backend="shifted")
    p99 = sc._windowed_p99_ms()
    assert p99 is not None and p99 > 1000.0    # delta sees it at once
    for _ in range(10):            # recovery: fast again
        hist.observe(0.001, phase="total", backend="shifted")
    p99 = sc._windowed_p99_ms()
    assert p99 is not None and p99 < 100.0     # and lets go at once


def test_p99_trigger_scales_up_without_queue_pressure():
    clock = [0.0]
    sc = _scaler(lambda: clock[0], up_pressure=0.9, p99_up_ms=100.0,
                 up_ticks=1)
    assert sc.decide(_sig(0.0, p99_ms=250.0)).action == "up"
    sc2 = _scaler(lambda: clock[0], up_pressure=0.9, p99_up_ms=100.0,
                  up_ticks=1)
    assert sc2.decide(_sig(0.0, p99_ms=50.0)).action != "up"


# --------------------------------------------------- work-unit pricing math


def test_pricing_scales_with_pixels_and_iters():
    # min_units lowered so the floor doesn't mask the scaling law under
    # test (the default floor is itself tested below).
    p = WorkPricer(grid=(2, 2), platform="cpu", min_units=1e-9)
    small = {"rows": 64, "cols": 64, "filter": "blur3", "iters": 2}
    big = {"rows": 4096, "cols": 4096, "filter": "blur3", "iters": 2}
    # Pixel ratio is 4096x; the price ratio is intentionally smaller
    # (small sharded blocks are exchange-latency-bound, so their per-px
    # cost is higher — the model pricing real marginal cost, not a flat
    # per-px fee) but must still be decisively work-proportional.
    assert p.price(big) > 50 * p.price(small)
    twice = p.price({"rows": 4096, "cols": 4096, "filter": "blur3",
                     "iters": 4})
    assert twice == pytest.approx(2.0 * p.price(big), rel=0.05)


def test_pricing_floor_cache_and_garbage():
    p = WorkPricer(grid=(1, 1), platform="cpu", min_units=1e-3)
    tiny = p.price({"rows": 2, "cols": 2, "filter": "blur3", "iters": 1})
    assert tiny == 1e-3                      # floored, still metered
    assert p.price({"rows": "garbage"}) == 1e-3   # malformed -> floor
    body = {"rows": 512, "cols": 512, "filter": "blur3", "iters": 3}
    assert p.price(body) == p.price(dict(body))   # cache: stable value


def test_converge_jobs_price_their_work_budget():
    p = WorkPricer(grid=(1, 2), platform="cpu")
    jac = {"rows": 1024, "cols": 1024, "filter": "blur3",
           "solver": "jacobi", "max_iters": 2000, "quantize": False}
    mg = dict(jac, solver="multigrid")
    pj, pm = p.price(jac, converge=True), p.price(mg, converge=True)
    # Same fine-grid work budget: the two solvers price within a small
    # factor of each other (the V-cycle adds transfer overhead), and
    # both dwarf a thumbnail request.
    assert 0.5 * pj < pm < 2.0 * pj
    assert pm > 1000 * p.price({"rows": 48, "cols": 64,
                                "filter": "blur3", "iters": 2})
    # Budget linearity: half the max_iters, about half the price.
    half = p.price(dict(mg, max_iters=1000), converge=True)
    assert half == pytest.approx(0.5 * pm, rel=0.1)


def test_mg_pricing_mirror_tracks_solver_schedule():
    """The jax-free cost-model mirror must track solvers.multigrid's
    actual schedule: constants pinned, work units per cycle within
    tolerance of the real planner's accounting."""
    from parallel_convolution_tpu.solvers import multigrid

    assert costmodel.MG_PRE_SWEEPS == multigrid.NU_PRE
    assert costmodel.MG_POST_SWEEPS == multigrid.NU_POST
    assert costmodel.MG_COARSE_SWEEPS == multigrid.NU_COARSE
    assert costmodel.MG_MIN_EXTENT == multigrid.MG_MIN_EXTENT
    assert costmodel.MG_MAX_LEVELS == multigrid.MG_MAX_LEVELS
    mesh = _mesh((1, 2))
    levels = multigrid.plan_levels(mesh, (96, 64), 1, "zero", None)
    real_wu = multigrid.cycle_work_units(levels)
    hw = costmodel.hardware_for("cpu")
    _, wu = costmodel.predict_mg_cycle_seconds(
        (1, 96, 64), (1, 2), 3, "f32", False, hw, levels=len(levels))
    assert wu == pytest.approx(real_wu, rel=0.25)


# ------------------------------------------------- priced buckets & quotas


def test_token_bucket_debt_admits_bigger_than_burst_jobs():
    clock = [0.0]
    b = TokenBucket(rate=1.0, burst=2.0, clock=lambda: clock[0])
    ok, _ = b.try_take(5.0)          # bigger than burst: full bucket pays
    assert ok and b.level() == pytest.approx(-3.0)
    ok, retry = b.try_take(0.5)      # in debt: refused with honest wait
    assert not ok and retry == pytest.approx(3.5)
    clock[0] = 3.6                   # debt refills at rate
    ok, _ = b.try_take(0.5)
    assert ok
    # But a PARTIAL bucket never grants an oversized job (debt needs a
    # full bucket): otherwise burst would stop meaning anything.
    b2 = TokenBucket(rate=1.0, burst=2.0, clock=lambda: clock[0])
    assert b2.try_take(1.5)[0]
    ok, _ = b2.try_take(5.0)
    assert not ok


def test_quotas_charge_and_refund_work_units():
    clock = [0.0]
    q = TenantQuotas(rate=1.0, burst=4.0, clock=lambda: clock[0])
    ok, _ = q.take("t", 3.0)
    assert ok and q.bucket("t").level() == pytest.approx(1.0)
    ok, _ = q.take("t", 3.0)         # only 1 token left
    assert not ok
    q.refund("t", 3.0)
    assert q.bucket("t").level() == pytest.approx(4.0)


def test_router_charges_priced_units_and_stamps_cost():
    img = _img()
    pricer = WorkPricer(grid=(1, 2), platform="cpu", min_units=1.0)
    # min_units=1.0 makes every request cost exactly 1 unit here, so the
    # bucket math is deterministic: burst 2 -> third request sheds.
    quotas = TenantQuotas(rate=0.001, burst=2.0)
    router = ReplicaRouter([InProcessReplica(_factory(), name="r0")],
                           quotas=quotas, pricer=pricer,
                           start_health=False)
    try:
        seen = []
        for i in range(3):
            status, wire = router.request(
                _body(img, iters=1, request_id=f"c{i}"), tenant="t")
            seen.append(wire)
        assert seen[0]["ok"] and seen[1]["ok"]
        assert seen[0]["router"]["cost_units"] == 1.0
        shed = seen[2]
        assert shed["rejected"] == "tenant_quota" and shed["retryable"]
        assert shed["cost_units"] == 1.0
        assert shed["retry_after_s"] > 0
    finally:
        router.close()


# --------------------------------------------- pool mutation & warm placement


def test_prewarm_flat_compile_on_joining_replica():
    img = _img()
    router = ReplicaRouter([InProcessReplica(_factory(), name="r0")],
                           start_health=False)
    try:
        for it in (1, 2, 3):
            status, wire = router.request(
                _body(img, iters=it, request_id=f"w{it}"))
            assert wire["ok"], wire

        def tfactory(name):
            return InProcessReplica(_factory(), name=name)

        sc = AutoScaler(router, tfactory, min_replicas=1, max_replicas=2,
                        up_ticks=1, down_ticks=1, cooldown_s=0.0)
        name = sc.scale_up()
        router.poll_once()
        newcomer = router.replica(name)
        eng = newcomer.service.engine
        # Pre-warm happened BEFORE ring join: whatever is resident now
        # was compiled off the observatory's shard replay.
        shard = [it for it in (1, 2, 3)
                 if router.ring.candidates(route_key(
                     _body(img, iters=it)))[0] == name]
        resident = {k.iters for k in eng._entries}
        assert set(shard) <= resident, (shard, resident)
        before = {k.iters: e.compiles for k, e in eng._entries.items()}
        assert all(v == 1 for v in before.values())
        # Remapped traffic for the shard keys lands warm: the per-key
        # compile ledger stays EXACTLY flat (max_batch=1 pool).
        for rep in range(3):
            for it in shard:
                status, wire = router.request(
                    _body(img, iters=it, request_id=f"p{rep}x{it}"))
                assert wire["ok"] and wire["router"]["replica"] == name
        after = {k.iters: e.compiles for k, e in eng._entries.items()}
        assert all(after[it] == before[it] for it in shard), (before,
                                                             after)
        assert eng.stats["compiles"] == len(before)
        # Scale-down drains the newcomer back out; the pool keeps
        # serving and only the leaver's keys re-home.
        assert sc.scale_down() == name
        assert router.ring.members() == ["r0"]
        status, wire = router.request(_body(img, iters=1,
                                            request_id="post"))
        assert wire["ok"]
    finally:
        router.close()


def test_remove_replica_guards_and_drain():
    img = _img()
    reps = [InProcessReplica(_factory(), name=f"r{i}") for i in range(2)]
    router = ReplicaRouter(reps, start_health=False)
    try:
        with pytest.raises(KeyError):
            router.remove_replica("nope")
        info = router.remove_replica("r1", drain_s=1.0)
        assert info["drained"] and router.ring.members() == ["r0"]
        with pytest.raises(ValueError):
            router.remove_replica("r0")
        status, wire = router.request(_body(img, iters=1))
        assert wire["ok"]
    finally:
        router.close()


def test_add_replica_rejects_duplicate_names():
    router = ReplicaRouter([InProcessReplica(_factory(), name="r0")],
                           start_health=False)
    try:
        with pytest.raises(ValueError):
            router.add_replica(InProcessReplica(_factory(), name="r0"))
    finally:
        router.close()


def test_router_stats_expose_autoscaler_inputs():
    img = _img()
    router = ReplicaRouter([InProcessReplica(_factory(), name="r0")],
                           start_health=False)
    try:
        status, wire = router.request(_body(img, iters=2))
        assert wire["ok"]
        router.poll_once()
        snap = router.snapshot()
        rep = snap["replicas"]["r0"]
        assert rep["in_flight"] == 0
        assert rep["queue_depth"] == 0
        assert rep["warm_keys"] == 1       # the served key is resident
        assert rep["in_ring"] is True
        assert snap["observed_keys"] == 1  # the observatory saw it
    finally:
        router.close()


def test_service_readiness_reports_warm_keys_and_progressive():
    svc = _factory()()
    try:
        ready, payload = svc.readiness()
        assert ready
        assert payload["warm_keys"] == 0
        assert payload["progressive_active"] == 0
        assert payload["progressive_bound"] == svc.max_progressive
    finally:
        svc.close()


# ------------------------------------------------- greedy-tenant isolation


def test_greedy_converge_tenant_is_priced_out_and_polite_p99_bounded():
    img = _img()
    pricer = WorkPricer(grid=(1, 2), platform="cpu")
    big_job = {"rows": 128, "cols": 128, "mode": "grey",
               "filter": "blur3", "solver": "multigrid",
               "max_iters": 120, "tol": 0.0, "quantize": False,
               "storage": "f32", "check_every": 1}
    big_cost = pricer.price(big_job, converge=True)
    small_cost = pricer.price({"rows": 32, "cols": 48, "mode": "grey",
                               "filter": "blur3", "iters": 1})
    assert big_cost > 10 * small_cost   # work-unit pricing premise
    quotas = TenantQuotas(rate=5.0, burst=8.0,
                          overrides={"greedy": (big_cost / 100.0,
                                                big_cost * 1.2)})
    router = ReplicaRouter([InProcessReplica(_factory(), name="r0")],
                           quotas=quotas, pricer=pricer,
                           start_health=False)
    try:
        big = {"image_b64": base64.b64encode(np.ascontiguousarray(
            imageio.generate_test_image(128, 128, "grey", seed=3)
        ).tobytes()).decode("ascii"), **{
            k: v for k, v in big_job.items()}}
        # First big job: admitted (debt semantics), runs in background.
        status, rows = router.converge(dict(big, request_id="g1"),
                                       tenant="greedy")
        assert status == 200
        drained = threading.Event()

        def drain():
            for _ in rows:
                pass
            drained.set()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        # Second big job while the first runs: priced out, typed shed
        # carrying the work-unit bill.
        status2, rows2 = router.converge(dict(big, request_id="g2"),
                                         tenant="greedy")
        shed = next(iter(rows2))
        assert shed["rejected"] == "tenant_quota" and shed["retryable"]
        assert shed["cost_units"] == pytest.approx(big_cost, abs=1e-6)
        # The polite tenant keeps serving small requests with a bounded
        # p99 while the admitted V-cycle job occupies the pool.
        lats = []
        for i in range(12):
            t0 = time.perf_counter()
            status, wire = router.request(
                _body(img, iters=1, request_id=f"pol{i}"),
                tenant="polite")
            lats.append(time.perf_counter() - t0)
            assert wire["ok"], wire
            assert wire.get("rejected") != "tenant_quota"
        lats.sort()
        assert lats[-1] < 10.0   # bounded: the OTHER big jobs were
        #                          priced out, so the queue never piles
        t.join(120)
        assert drained.is_set()
    finally:
        router.close()


# ----------------------------------------------------- perf_gate extensions


def _gate(tmp_path, rows, extra=()):
    hist = tmp_path / "hist.jsonl"
    row_files = []
    for i, r in enumerate(rows):
        p = tmp_path / f"row{i}.json"
        p.write_text(json.dumps(r))
        row_files += ["--row", str(p)]
    return subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"),
         "--history", str(hist), *row_files, "--quiet", *extra],
        capture_output=True, text=True)


def test_perf_gate_latency_rows_fail_on_2x_p99(tmp_path):
    base = {"workload": "curve", "gate_metric": "latency",
            "p99_ms": 80.0, "offered_rps": 20.0,
            "effective_backend": "shifted", "mesh": "1x2"}
    assert _gate(tmp_path, [base], ["--update"]).returncode == 0
    assert _gate(tmp_path, [base]).returncode == 0
    assert _gate(tmp_path, [dict(base, p99_ms=160.0)]).returncode == 1
    # An IMPROVEMENT (lower latency) never fails.
    assert _gate(tmp_path, [dict(base, p99_ms=40.0)]).returncode == 0


def test_perf_gate_rps_and_topology_key_lanes(tmp_path):
    out = tmp_path / "report.json"
    row = {"workload": "w", "gate_metric": "latency", "p99_ms": 50.0,
           "offered_rps": 15.0, "effective_backend": "shifted",
           "mesh": "2x4", "hosts": 4, "slice_topology": "4x8:v5e"}
    r = _gate(tmp_path, [row], ["--update", "--out", str(out)])
    assert r.returncode == 0
    key = json.loads(out.read_text())["verdicts"][0]["key"]
    assert "rps=15" in key and "hosts=4" in key and "4x8:v5e" in key
    # Single-host rows stay on their historical unsuffixed keys.
    row1 = dict(row, hosts=1, slice_topology="1x8:cpu")
    r = _gate(tmp_path, [row1], ["--out", str(out)])
    key1 = json.loads(out.read_text())["verdicts"][0]["key"]
    assert "hosts=" not in key1 and "rps=15" in key1


def test_topology_stamp_shape():
    from parallel_convolution_tpu.utils.platform import topology

    t = topology(_mesh((1, 2)))
    assert t["hosts"] == 1
    assert t["slice_topology"].startswith("1x2:")
