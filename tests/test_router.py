"""Replica-set router: ring, breaker, quota, failover, progressive.

The round-14 acceptance properties (ISSUE 9), all on the 8-virtual-device
CPU mesh:

* consistent-hash stability — adding/removing one replica remaps only
  that replica's keys;
* circuit breaker walks closed → open → half-open → closed
  deterministically (injected clock), and a request's own contract bug
  never opens a replica's circuit;
* hedge dedup — two submissions with one request_id cost ONE device
  execution (engine batch/compile counters flat);
* tenant bucket isolation — a greedy tenant sheds typed retryable
  ``tenant_quota`` while another tenant's stream completes untouched;
* the progressive stream ends with the EXACT final image bytes of the
  equivalent non-progressive run;
* serve-through-reshape — the router keeps serving (spill + retryable
  sheds only) while one replica walks the round-10 reshape ladder.
"""

from __future__ import annotations

import base64
import threading

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.resilience import degrade, faults
from parallel_convolution_tpu.resilience.breaker import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
)
from parallel_convolution_tpu.serving.frontend import encode_response
from parallel_convolution_tpu.serving.router import (
    HashRing, InProcessReplica, ReplicaRouter, TenantQuotas, TokenBucket,
    route_key,
)
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Rejected, Request, Snapshot,
)
from parallel_convolution_tpu.utils import imageio


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey"}
    body.update(kw)
    return body


def _factory(shape=(1, 2), **kw):
    kw.setdefault("max_delay_s", 0.002)

    def make():
        return ConvolutionService(_mesh(shape), **kw)

    return make


def _router(n=2, shape=(1, 2), **kw):
    kw.setdefault("poll_interval_s", 0.05)
    kw.setdefault("breaker_cooldown_s", 0.2)
    reps = [InProcessReplica(_factory(shape), name=f"r{i}")
            for i in range(n)]
    return ReplicaRouter(reps, **kw)


# ----------------------------------------------------------- hash ring


def test_ring_remaps_only_touched_replica_keys():
    keys = [f"key-{i}" for i in range(240)]
    ring = HashRing(["a", "b", "c"])
    before = {k: ring.candidates(k)[0] for k in keys}
    assert set(before.values()) == {"a", "b", "c"}  # all replicas used

    # Removal: every key NOT homed on c keeps its home.
    ring.remove("c")
    after_rm = {k: ring.candidates(k)[0] for k in keys}
    for k in keys:
        if before[k] != "c":
            assert after_rm[k] == before[k]
        else:
            assert after_rm[k] in ("a", "b")

    # Addition: keys either keep their home or move to the NEW member.
    ring.add("c")
    restored = {k: ring.candidates(k)[0] for k in keys}
    assert restored == before  # same membership -> same mapping
    ring.add("d")
    after_add = {k: ring.candidates(k)[0] for k in keys}
    for k in keys:
        assert after_add[k] in (before[k], "d")
    assert any(after_add[k] == "d" for k in keys)


def test_ring_candidates_cover_all_members_home_first():
    ring = HashRing(["a", "b", "c"], vnodes=16)
    order = ring.candidates("some-key")
    assert sorted(order) == ["a", "b", "c"]
    assert order[0] == ring.candidates("some-key")[0]  # deterministic


def test_route_key_covers_compile_identity_not_content():
    img = _img()
    b1 = _body(img, filter="blur3", iters=2)
    b2 = _body(_img(seed=99), filter="blur3", iters=2)   # other CONTENT
    b3 = _body(img, filter="blur3", iters=3)             # other key
    assert route_key(b1) == route_key(b2)
    assert route_key(b1) != route_key(b3)


# ------------------------------------------------------ circuit breaker


def test_breaker_walks_closed_open_halfopen_closed():
    clock = [0.0]
    br = CircuitBreaker(threshold=3, cooldown_s=5.0,
                        clock=lambda: clock[0])
    assert br.state() == CLOSED and br.allow()
    for _ in range(2):
        br.record_failure(ConnectionError("down"))
    assert br.state() == CLOSED          # below threshold
    br.record_success()
    br.record_failure(ConnectionError("down"))
    assert br.state() == CLOSED          # success reset the streak
    for _ in range(3):
        br.record_failure(ConnectionError("down"))
    assert br.state() == OPEN
    assert not br.allow()                # cooling down
    clock[0] += 5.0
    assert br.allow()                    # the half-open probe slot
    assert br.state() == HALF_OPEN
    assert not br.allow()                # one probe at a time
    br.record_failure(ConnectionError("still down"))
    assert br.state() == OPEN            # probe failed -> re-open
    clock[0] += 5.0
    assert br.allow()
    br.record_success()
    assert br.state() == CLOSED and br.allow()


def test_breaker_ignores_terminal_classified_failures():
    br = CircuitBreaker(threshold=1, cooldown_s=5.0)
    br.record_failure(ValueError("the request's own contract bug"))
    assert br.state() == CLOSED
    br.record_failure(ConnectionError("replica down"))
    assert br.state() == OPEN


# -------------------------------------------------------- token buckets


def test_token_bucket_refills_on_wall_clock():
    clock = [0.0]
    b = TokenBucket(rate=2.0, burst=2.0, clock=lambda: clock[0])
    assert b.try_take()[0] and b.try_take()[0]
    ok, retry_after = b.try_take()
    assert not ok and retry_after == pytest.approx(0.5)
    clock[0] += 0.5
    assert b.try_take()[0]               # one token refilled
    b.refund()
    assert b.try_take()[0]               # refund restored it


def test_tenant_buckets_are_isolated():
    clock = [0.0]
    q = TenantQuotas(rate=1.0, burst=1.0, clock=lambda: clock[0])
    assert q.take("greedy")[0]
    assert not q.take("greedy")[0]       # greedy's bucket is empty
    for _ in range(3):
        ok, _ = q.take("victim")
        clock[0] += 1.0
        assert ok                        # victim's bucket untouched


# ---------------------------------------------- frontend reject semantics


@pytest.mark.parametrize("reason,status,retryable", [
    ("queue_full", 429, True),
    ("tenant_quota", 429, True),
    ("resharding", 503, True),
    ("replica_unavailable", 503, True),
    ("deadline", 429, False),
    ("invalid", 400, False),
    ("error", 500, False),
    ("timeout", 504, False),
])
def test_reject_status_and_retryable_split(reason, status, retryable):
    rej = Rejected(reason, "rq1", detail="x")
    got_status, wire = encode_response(rej)
    assert got_status == status
    assert wire["retryable"] is retryable
    if retryable:
        assert wire["retry_after_s"] > 0   # the back-off hint
    else:
        assert "retry_after_s" not in wire


# ------------------------------------------------------- request dedup


def test_hedge_dedup_one_device_execution_per_request_id():
    svc = ConvolutionService(_mesh(), max_delay_s=0.02)
    img = _img()
    req = Request(image=img, iters=2, request_id="hedge-1")
    results = []
    lock = threading.Lock()

    def submit():
        r = svc.submit(req, timeout=120)
        with lock:
            results.append(r)

    threads = [threading.Thread(target=submit) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    assert len(results) == 4 and all(r.ok for r in results)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    for r in results:
        np.testing.assert_array_equal(r.image, want)
    # One device execution, one image: the four hedges shared one slot.
    assert svc.engine.stats["images"] == 1
    assert svc.engine.stats["batches"] == 1
    assert svc.stats["deduped"] == 3
    compiles = svc.engine.stats["compiles"]
    # A later duplicate (completed entry) is served from the ledger with
    # ZERO additional device work or compilation.
    r = svc.submit(req, timeout=120)
    assert r.ok and svc.engine.stats["images"] == 1
    assert svc.engine.stats["compiles"] == compiles
    svc.close()


def test_dedup_rejected_outcome_does_not_stick():
    svc = ConvolutionService(_mesh(), max_delay_s=0.02)
    bad = Request(image=_img(), filter_name="nope", request_id="rid-x")
    r1 = svc.submit(bad, timeout=60)
    assert isinstance(r1, Rejected) and r1.reason == "invalid"
    good = Request(image=_img(), iters=1, request_id="rid-x")
    r2 = svc.submit(good, timeout=120)
    assert r2.ok   # the retry after a shed re-executed
    svc.close()


# ------------------------------------------------- routing and failover


def test_router_partitions_keys_and_serves_oracle_bytes():
    router = _router(n=2)
    img = _img()
    want = {it: oracle.run_serial_u8(img, filters.get_filter("blur3"), it)
            for it in (1, 2)}
    homes = {}
    for it in (1, 2):
        for _ in range(2):
            status, wire = router.request(
                _body(img, filter="blur3", iters=it))
            assert status == 200 and wire["ok"], wire
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(img.shape)
            np.testing.assert_array_equal(got, want[it])
            homes.setdefault(it, wire["router"]["replica"])
            # same key -> same replica, every time
            assert wire["router"]["replica"] == homes[it]
            assert wire["router"]["home"] == homes[it]
    # each key resident on exactly the one replica that serves it
    for it, home in homes.items():
        for name in ("r0", "r1"):
            resident = [k.iters for k in router.replica(
                name).service.engine._entries]
            assert (it in resident) == (name == home)
    router.close()


def test_router_failover_on_killed_home_byte_identical():
    router = _router(n=3)
    img = _img()
    body = _body(img, filter="blur3", iters=2)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    status, wire = router.request(dict(body))
    assert status == 200 and wire["ok"]
    home = wire["router"]["replica"]
    router.replica(home).kill()
    status, wire = router.request(dict(body))
    assert status == 200 and wire["ok"], wire
    assert wire["router"]["replica"] != home
    assert (wire["router"]["failovers"] >= 1
            or wire["router"]["spills"] >= 1)
    got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                        np.uint8).reshape(img.shape)
    np.testing.assert_array_equal(got, want)
    # Revived home takes its keys back (ring membership never changed).
    router.replica(home).revive()
    router.poll_once()
    status, wire = router.request(dict(body))
    assert status == 200 and wire["router"]["replica"] == home
    router.close()


def test_router_all_replicas_down_typed_unavailable():
    router = _router(n=2)
    for name in ("r0", "r1"):
        router.replica(name).kill()
    status, wire = router.request(_body(_img(), iters=1))
    assert status == 503
    assert wire["rejected"] == "replica_unavailable"
    assert wire["retryable"] is True and wire["retry_after_s"] > 0
    router.close()


def test_router_tenant_isolation_greedy_cannot_shed_victim():
    router = _router(
        n=2, quotas=TenantQuotas(rate=1.0, burst=2.0,
                                 overrides={"victim": (0.0, 1.0)}))
    img = _img()
    body = _body(img, filter="blur3", iters=1)
    greedy_sheds = 0
    for _ in range(6):
        status, wire = router.request(dict(body), tenant="greedy")
        if not wire.get("ok"):
            assert wire["rejected"] == "tenant_quota", wire
            assert wire["retryable"] is True
            assert wire["retry_after_s"] > 0
            assert status == 429
            greedy_sheds += 1
    assert greedy_sheds >= 3   # burst 2, refill 1/s: the flood sheds
    for _ in range(4):         # ...and the victim never notices
        status, wire = router.request(dict(body), tenant="victim")
        assert status == 200 and wire["ok"], wire
    assert router.stats["rejected_tenant_quota"] == greedy_sheds
    router.close()


def test_router_readyz_reflects_replica_states():
    router = _router(n=2)
    router.poll_once()
    status, payload = router.readyz()
    assert status == 200 and payload["ready"]
    assert set(payload["replicas"]) == {"r0", "r1"}
    router.replica("r0").kill()
    router.replica("r1").kill()
    router.poll_once()
    status, payload = router.readyz()
    assert status == 503 and not payload["ready"]
    router.close()


# --------------------------------------------------- progressive results


def test_progressive_stream_ends_with_exact_final_bytes():
    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    img = _img(40, 56, seed=3)
    tol, max_iters, check_every = 0.05, 45, 10
    stream = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=tol, max_iters=max_iters, check_every=check_every)
    rows = list(stream)
    assert all(isinstance(r, Snapshot) for r in rows)
    assert rows[-1].final and not any(r.final for r in rows[:-1])
    # the diff trajectory is monotone non-increasing for this smoother
    diffs = [r.diff for r in rows[:-1]]
    assert diffs == sorted(diffs, reverse=True)
    # exact final bytes: the non-progressive run of the same job
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    want, want_iters = step.sharded_converge(
        x, filters.get_filter("jacobi3"), tol=tol, max_iters=max_iters,
        check_every=check_every, mesh=svc.engine.mesh, quantize=False)
    want_u8 = np.clip(np.rint(np.asarray(want)), 0,
                      255).astype(np.uint8)[0]
    assert rows[-1].iters == int(want_iters)
    np.testing.assert_array_equal(rows[-1].image, want_u8)
    # a second job on the warm key compiles nothing new
    compiles = svc.engine.stats["compiles"]
    rows2 = list(svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=tol, max_iters=max_iters, check_every=check_every))
    assert rows2[-1].final
    np.testing.assert_array_equal(rows2[-1].image, want_u8)
    assert svc.engine.stats["compiles"] == compiles
    svc.close()


def test_progressive_through_router_and_invalid_typed():
    router = _router(n=2, shape=(2, 2))
    img = _img(40, 56, seed=3)
    cbody = _body(img, filter="jacobi3", tol=0.05, max_iters=30,
                  check_every=10)
    status, rows = router.converge(dict(cbody))
    rows = list(rows)
    assert status == 200
    kinds = [r["kind"] for r in rows]
    assert kinds[-1] == "final" and "snapshot" in kinds
    assert all(r["router"]["replica"] == rows[0]["router"]["replica"]
               for r in rows)
    # malformed: typed invalid, not a stream — and NOT replica-health
    # evidence: the client's own contract bug must count no failover
    # and feed no breaker (same taxonomy as the request path).
    failovers_before = router.stats["failovers"]
    status, rows = router.converge(
        _body(img, filter="jacobi3", tol="not-a-number"))
    rows = list(rows)
    assert status == 400 and rows[0]["rejected"] == "invalid"
    assert router.stats["failovers"] == failovers_before
    assert all(rep.breaker.state() == "closed"
               and rep.breaker.snapshot()["failures"] == 0
               for rep in router._replicas.values())
    router.close()


def test_progressive_slot_released_when_stream_dropped_unstarted():
    """An admitted stream abandoned before its first row must free its
    max_progressive slot (a plain generator's finally never runs if the
    body is never entered) — via close() and via the GC finalizer."""
    svc = ConvolutionService(_mesh(), max_delay_s=0.002,
                             max_progressive=1)
    img = _img()

    def job():
        return svc.submit_progressive(
            Request(image=img, filter_name="jacobi3", quantize=False),
            tol=1e-6, max_iters=20, check_every=10)

    s1 = job()
    assert not isinstance(s1, Rejected)
    s1.close()                          # dropped un-started, explicitly
    s2 = job()
    assert not isinstance(s2, Rejected)  # the slot came back
    del s2                               # dropped un-started, via GC
    import gc

    gc.collect()
    s3 = job()
    assert not isinstance(s3, Rejected)
    assert list(s3)[-1].final            # and a real run still works
    svc.close()


def test_progressive_bounded_and_resharding_typed():
    svc = ConvolutionService(_mesh(), max_delay_s=0.002,
                             max_progressive=1)
    img = _img()
    stream1 = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=1e-6, max_iters=30, check_every=10)
    assert not isinstance(stream1, Rejected)
    next(iter_ := iter(stream1))          # job 1 occupies the only slot
    r = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=1e-6, max_iters=30, check_every=10)
    assert isinstance(r, Rejected) and r.reason == "queue_full"
    assert r.retryable
    list(iter_)                           # drain job 1, slot frees
    r2 = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=1e-6, max_iters=10, check_every=10)
    assert not isinstance(r2, Rejected)
    list(r2)
    svc.close()


# ------------------------------------------------- serve-through-reshape


def test_router_serves_through_replica_reshape():
    router = _router(n=2, shape=(2, 2))
    img = _img()
    body = _body(img, filter="blur3", iters=2)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    status, wire = router.request(dict(body))
    assert status == 200
    home = wire["router"]["replica"]

    stop = threading.Event()
    outcomes, lock = [], threading.Lock()

    def traffic():
        while not stop.is_set():
            s, w = router.request(dict(body))
            with lock:
                outcomes.append(w)

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    # The round-10 ladder mid-traffic: drain, swap 2x2 -> 1x2, re-warm.
    info = router.replica(home).service.reshape("1x2")
    assert info["grid"] == (1, 2)
    stop.set()
    t.join(120)
    # Post-reshape the router still serves this key, byte-identically.
    status, wire = router.request(dict(body))
    assert status == 200 and wire["ok"]
    got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                        np.uint8).reshape(img.shape)
    np.testing.assert_array_equal(got, want)
    # Everything during the window either completed byte-identical or
    # shed typed-retryable (resharding spill paths) — never an error.
    for w in outcomes:
        if w.get("ok"):
            got = np.frombuffer(base64.b64decode(w["image_b64"]),
                                np.uint8).reshape(img.shape)
            np.testing.assert_array_equal(got, want)
        else:
            assert w.get("retryable"), w
    router.close()
