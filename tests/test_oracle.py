import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle


def _naive_correlate(img, taps):
    """Brutally simple per-pixel double-precision reference for the oracle."""
    k = taps.shape[0]
    r = k // 2
    H, W = img.shape[:2]
    pad = [(r, r), (r, r)] + [(0, 0)] * (img.ndim - 2)
    p = np.pad(img.astype(np.float64), pad)
    out = np.zeros(img.shape, np.float64)
    for y in range(H):
        for x in range(W):
            win = p[y : y + k, x : x + k]
            if img.ndim == 2:
                out[y, x] = float((win * taps).sum())
            else:
                out[y, x] = np.einsum("ijc,ij->c", win, taps.astype(np.float64))
    return out


@pytest.mark.parametrize("name", ["blur3", "gaussian5", "edge3", "identity3"])
def test_correlate_matches_naive_grey(grey_small, name):
    f = filters.get_filter(name)
    got = oracle.correlate_once(grey_small.astype(np.float32), f)
    want = _naive_correlate(grey_small, f.taps)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_correlate_matches_naive_rgb(rgb_small):
    f = filters.get_filter("blur3")
    got = oracle.correlate_once(rgb_small.astype(np.float32), f)
    want = _naive_correlate(rgb_small, f.taps)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_identity_filter_is_identity(grey_small):
    f = filters.get_filter("identity3")
    out = oracle.run_serial_u8(grey_small, f, iters=5)
    np.testing.assert_array_equal(out, grey_small)


def test_zero_padding_darkens_borders(grey_small):
    f = filters.get_filter("blur3")
    bright = np.full_like(grey_small, 200)
    out = oracle.convolve_once_u8(bright, f)
    # interior preserved exactly (filter sums to 1, dyadic)
    assert out[5, 5] == 200
    # corners lose 7/16 of mass to the zero ghost ring
    assert out[0, 0] == np.uint8(np.rint(200 * 9 / 16))


def test_quantize_semantics():
    acc = np.array([-3.2, -0.4, 0.5, 1.5, 254.5, 255.5, 300.0], np.float32)
    # rint is half-to-even: 0.5→0, 1.5→2, 254.5→254
    np.testing.assert_array_equal(
        oracle.quantize_u8(acc), np.array([0, 0, 0, 2, 254, 255, 255], np.uint8)
    )


def test_iterated_blur_converges_to_flat():
    f = filters.get_filter("jacobi3")
    img = np.full((16, 16), 100.0, np.float32)
    out, iters = oracle.run_to_convergence_f32(img, f, tol=1e-6, max_iters=50)
    # A constant field is not a fixed point (zero boundary drains mass),
    # but convergence machinery must terminate within max_iters.
    assert iters <= 50


def test_convergence_fixed_point_immediate():
    f = filters.get_filter("identity3")
    img = np.arange(64, dtype=np.float32).reshape(8, 8)
    out, iters = oracle.run_to_convergence_f32(img, f, tol=1e-6, max_iters=100,
                                               check_every=4)
    assert iters == 4  # first check window detects the fixed point
    np.testing.assert_array_equal(out, img)


def test_run_serial_u8_multiple_iters_stays_u8(rgb_small):
    f = filters.get_filter("blur3")
    out = oracle.run_serial_u8(rgb_small, f, iters=3)
    assert out.dtype == np.uint8 and out.shape == rgb_small.shape
    # blur must actually change a noisy image
    assert not np.array_equal(out, rgb_small)
