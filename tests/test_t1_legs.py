"""t1_legs.json schema gate (ISSUE 18 satellite).

``scripts/t1_legs.json`` is the machine-readable registry the smoke
driver and ``run_t1.sh --list-legs`` read. The contract enforced here:

* every leg's ``cmd`` starts with an existing script, and any
  ``--flag`` it passes to ``run_t1.sh`` is actually handled there;
* leg names are unique; evidence ``done_file`` outputs are unique, so
  two legs can never race on one artifact;
* ``done_pattern`` is present iff ``done_file`` is (a pattern without
  a file to grep — or a file nobody gates on — is a dead leg);
* timeouts are positive ints, and legs that declare a done_file keep
  it under ``evidence/``.
"""

from __future__ import annotations

import json
import re
import subprocess
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
LEGS_PATH = ROOT / "scripts" / "t1_legs.json"


def _legs():
    return json.loads(LEGS_PATH.read_text())


def test_registry_parses_and_is_nonempty():
    legs = _legs()
    assert isinstance(legs, list) and len(legs) >= 10
    for leg in legs:
        assert set(leg) <= {"name", "cmd", "done_file", "done_pattern",
                            "timeout"}, leg
        assert isinstance(leg["name"], str) and leg["name"]
        assert isinstance(leg["cmd"], list) and leg["cmd"]
        assert all(isinstance(a, str) for a in leg["cmd"])


def test_leg_names_unique():
    names = [leg["name"] for leg in _legs()]
    assert len(names) == len(set(names))


def test_cmds_reference_existing_scripts_and_real_flags():
    driver = (ROOT / "scripts" / "run_t1.sh").read_text()
    for leg in _legs():
        cmd = leg["cmd"]
        script = cmd[1] if cmd[0] in ("bash", "sh", "python") else cmd[0]
        assert (ROOT / script).is_file(), f"{leg['name']}: {script}"
        for arg in cmd[2:]:
            if arg.startswith("--") and script.endswith("run_t1.sh"):
                assert re.search(
                    rf'"\$\{{1:-\}}" = "{re.escape(arg)}"',
                    driver), f"{leg['name']}: {arg}"


def test_done_file_unique_under_evidence_and_pattern_iff_file():
    legs = _legs()
    done_files = [leg["done_file"] for leg in legs if "done_file" in leg]
    assert len(done_files) == len(set(done_files))
    for leg in legs:
        has_file = "done_file" in leg
        assert has_file == ("done_pattern" in leg), leg["name"]
        if has_file:
            assert leg["done_file"].startswith("evidence/"), leg["name"]
            assert isinstance(leg["done_pattern"], str)
            assert leg["done_pattern"]
    # The full-suite leg is the one sanctioned file-less entry.
    bare = [leg["name"] for leg in legs if "done_file" not in leg]
    assert bare == ["tier1"]


def test_timeouts_positive_ints():
    for leg in _legs():
        assert isinstance(leg["timeout"], int) and leg["timeout"] > 0


def test_list_legs_prints_every_leg():
    out = subprocess.run(
        ["bash", "scripts/run_t1.sh", "--list-legs"],
        cwd=ROOT, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for leg in _legs():
        assert leg["name"] in out.stdout
