"""Rank-3 volumetric subsystem (round 23): halo, forms, transfer.

Proof surfaces, every one against an INDEPENDENT reference:

1. 6-FACE HALO — ``volumes.halo3.volume_halo_exchange`` run inside
   ``shard_map`` reproduces, per block and byte-for-byte, the slices of
   the globally ``np.pad``-ghosted cube (``oracle3.pad_global``): zero
   and periodic, a generic grid, BOTH 1-long-axis grids (self-wrap on
   the unsharded axis), and an all-rim geometry where every cell of
   every block sits within the ghost radius of a block face.
2. FORMS vs ORACLE — all six registered rank-3 forms (7/25-point FD,
   their _stack twins, wave, Gray–Scott) match ``oracle3.run_oracle``
   (global np.pad ghosting, float64 accumulation — a different
   algorithm AND different arithmetic) on a 2x4 mesh, both boundaries,
   including the zero-boundary pad-to-multiple rim.
3. BYTE IDENTITY — the _stack twins are bitwise equal to their planar
   siblings (same weighted terms, same fixed order), the forms are
   bitwise mesh-invariant (1x1 vs 2x4 vs 4x2), temporal fusion is
   invariant to 1 ulp (fused/unfused are different XLA programs), and
   the converge chunk math lands on the same bytes as the fixed-count
   runner (the property serving resumes lean on).
4. TRANSFER — rank-3 full-weighting restriction and trilinear
   prolongation vs explicit-loop NumPy formulas (3x3x3 tensor-product
   taps / per-cell neighbor means), both boundaries, including the
   odd-centered coarse-extent masking on the resident depth axis.
5. ERROR SURFACES + COST MODEL — typed resolution-time failures
   (boundary/shape/fuse/geometry) and the rank-3 bytes/cell and
   face-bytes attribution arithmetic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from parallel_convolution_tpu.obs import attribution
from parallel_convolution_tpu.parallel import kernels as kernel_forms
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.solvers import transfer
from parallel_convolution_tpu.tuning import costmodel
from parallel_convolution_tpu.utils.config import (
    BOUNDARIES, VOLUME_FIELDS, VOLUME_FORMS, VOLUME_RADII,
)
from parallel_convolution_tpu.utils.jax_compat import shard_map
from parallel_convolution_tpu.volumes import driver, halo3, oracle3

SPEC = P(None, None, "x", "y")


def _mesh(shape=(2, 4)):
    n = shape[0] * shape[1]
    return mesh_lib.make_grid_mesh(jax.devices()[:n], shape)


def _vol(rng, d, h, w, fields=VOLUME_FIELDS):
    # Bounded [0, 1): safe for the Gray–Scott cubic term.
    return rng.random((fields, d, h, w), dtype=np.float32)


# ------------------------------------------------------------ 6-face halo


@pytest.mark.parametrize("boundary", BOUNDARIES)
@pytest.mark.parametrize("grid,dhw,r", [
    ((2, 4), (3, 8, 8), 1),   # generic 2D decomposition
    ((1, 4), (2, 6, 8), 2),   # 1-long-axis: H unsharded (self-wrap)
    ((4, 1), (2, 8, 6), 2),   # 1-long-axis: W unsharded
    ((2, 4), (1, 4, 8), 1),   # all-rim: every cell within r of a face
])
def test_halo_exchange_matches_global_pad(grid, dhw, r, boundary):
    """Every block's 6-face-ghosted tile equals the matching window of
    the globally padded cube — including the 12 edge and 8 corner ghost
    regions the two-hop phase ordering must propagate."""
    R, C = grid
    D, H, W = dhw
    rng = np.random.default_rng(3)
    vol = _vol(rng, D, H, W)
    mesh = _mesh(grid)
    bh, bw = H // R, W // C
    fn = jax.jit(shard_map(
        lambda b: halo3.volume_halo_exchange(b, r, grid, boundary),
        mesh=mesh, in_specs=SPEC, out_specs=SPEC, check_vma=False))
    xs = jax.device_put(jnp.asarray(vol), driver.volume_sharding(mesh))
    out = np.asarray(fn(xs))
    assert out.shape == (VOLUME_FIELDS, D + 2 * r,
                         R * (bh + 2 * r), C * (bw + 2 * r))
    pg = oracle3.pad_global(vol, r, boundary)
    ph, pw = bh + 2 * r, bw + 2 * r
    for i in range(R):
        for j in range(C):
            got = out[:, :, i * ph:(i + 1) * ph, j * pw:(j + 1) * pw]
            want = pg[:, :, i * bh:i * bh + ph, j * bw:j * bw + pw]
            np.testing.assert_array_equal(got, want)


def test_halo_exchange_error_surfaces():
    blk = jnp.zeros((2, 4, 4, 4), jnp.float32)
    with pytest.raises(ValueError, match="boundary"):
        halo3.volume_halo_exchange(blk, 1, (1, 1), "moebius")
    with pytest.raises(ValueError, match="F, D, h, w"):
        halo3.volume_halo_exchange(blk[0], 1, (1, 1), "zero")
    with pytest.raises(ValueError, match="periodic depth wrap"):
        halo3.volume_halo_exchange(
            jnp.zeros((2, 2, 8, 8), jnp.float32), 3, (1, 1), "periodic")


# --------------------------------------------------------- forms vs oracle


@pytest.mark.parametrize("name", VOLUME_FORMS)
@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_forms_match_oracle_sharded(name, boundary):
    rng = np.random.default_rng(11)
    if boundary == "periodic":
        d, h, w = 6, 24, 40       # grid-divisible on 2x4
    else:
        d, h, w = 6, 22, 36       # pads to 24x40: the rim mask matters
    vol = _vol(rng, d, h, w)
    got = driver.volume_iterate(vol, name, 3, mesh=_mesh((2, 4)),
                                boundary=boundary)
    want = oracle3.run_oracle(vol, name, 3, boundary)
    assert got.shape == vol.shape and got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=0, atol=2e-5)


@pytest.mark.parametrize("base", ["fd7", "fd25"])
@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_stack_twins_byte_identical(base, boundary):
    """The _stack twins route the SAME weighted terms in the SAME fixed
    order — bitwise, not approximately."""
    rng = np.random.default_rng(5)
    vol = _vol(rng, 6, 24, 40)
    mesh = _mesh((2, 4))
    a = driver.volume_iterate(vol, base, 4, mesh=mesh, boundary=boundary)
    b = driver.volume_iterate(vol, base + "_stack", 4, mesh=mesh,
                              boundary=boundary)
    assert a.tobytes() == b.tobytes()


@pytest.mark.parametrize("name", ["fd7", "fd25", "wave", "grayscott"])
def test_forms_bitwise_mesh_invariant(name):
    """Same bytes on 1x1, 2x4 and 4x2 — the decomposition is invisible."""
    rng = np.random.default_rng(7)
    vol = _vol(rng, 6, 24, 40)
    outs = [driver.volume_iterate(vol, name, 3, mesh=_mesh(g))
            for g in ((1, 1), (2, 4), (4, 2))]
    for other in outs[1:]:
        assert outs[0].tobytes() == other.tobytes()


@pytest.mark.parametrize("name", ["fd7", "fd25", "wave", "grayscott"])
@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_temporal_fusion_is_invariant(name, boundary):
    """fuse=2 runs the same per-cell arithmetic on deeper ghosts — the
    r*T ghost schedule (and its shrinking-ring re-zero) reproduces the
    unfused result to 1 ulp.  (Not bitwise: the fused and unfused
    programs are DIFFERENT XLA compilations, whose multiply-adds may
    FMA-contract differently — byte identity is only doctrine within
    one compiled program shape, i.e. across forms/meshes of the same
    schedule, which the twin/mesh-invariance tests above pin.)"""
    rng = np.random.default_rng(9)
    vol = _vol(rng, 8, 24, 40)   # D >= radius*fuse for fd25 periodic
    mesh = _mesh((2, 4))
    a = driver.volume_iterate(vol, name, 4, mesh=mesh, boundary=boundary,
                              fuse=1)
    b = driver.volume_iterate(vol, name, 4, mesh=mesh, boundary=boundary,
                              fuse=2)
    np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)


def test_converge_chunks_match_fixed_count_bytes():
    """The converge chunk math (n-1 fused + one diff-forming step) lands
    on the identical bytes as the fixed-count runner at every chunking —
    the property byte-stable serving resumes are built on."""
    rng = np.random.default_rng(13)
    vol = _vol(rng, 4, 16, 16)
    mesh = _mesh((2, 4))
    want = driver.volume_iterate(vol, "fd7", 8, mesh=mesh)
    for check_every in (3, 4, 8):
        state, done, diff = driver.volume_converge(
            vol, "fd7", tol=0.0, max_iters=8, check_every=check_every,
            mesh=mesh)
        assert done == 8 and diff >= 0.0
        assert state.tobytes() == want.tobytes()


def test_converge_stream_yields_monotone_progress():
    rng = np.random.default_rng(17)
    vol = _vol(rng, 4, 16, 16)
    rows = list(driver.volume_converge_stream(
        vol, "fd7", tol=0.0, max_iters=9, check_every=4,
        mesh=_mesh((2, 4))))
    assert [r[1] for r in rows] == [4, 8, 9]
    # fd7 Jacobi on a fixed rhs contracts: diffs shrink monotonically.
    diffs = [r[2] for r in rows]
    assert diffs == sorted(diffs, reverse=True)


# ----------------------------------------------------- geometry + errors


def test_geometry_error_surfaces():
    mesh = _mesh((2, 4))
    rng = np.random.default_rng(1)
    with pytest.raises(ValueError, match="interleaved field pairs"):
        driver.volume_iterate(rng.random((3, 4, 8, 8)), "fd7", 1,
                              mesh=mesh)
    with pytest.raises(ValueError, match="grid-divisible"):
        driver.volume_iterate(_vol(rng, 4, 9, 8), "fd7", 1, mesh=mesh,
                              boundary="periodic")
    with pytest.raises(ValueError, match="fuse"):
        driver.volume_iterate(_vol(rng, 4, 8, 8), "fd7", 8, mesh=mesh,
                              fuse=8)   # ghost depth 8 > 4x2 blocks
    with pytest.raises(ValueError, match="no kernel form registered"):
        driver.volume_iterate(_vol(rng, 4, 8, 8), "fd9", 1, mesh=mesh)


# ------------------------------------------------------ rank-3 transfer


def _np_restrict3(x, boundary):
    """Independent full weighting: explicit 3x3x3 tensor-product taps on
    the globally padded cube, then the centering subsample + coarse
    extents."""
    F, D, H, W = x.shape
    mode = "wrap" if boundary == "periodic" else "constant"
    p = np.pad(x.astype(np.float64),
               ((0, 0), (1, 1), (1, 1), (1, 1)), mode=mode)
    t = np.array([0.25, 0.5, 0.25])
    out = np.zeros((F, D, H, W))
    for a in range(3):
        for b in range(3):
            for c in range(3):
                out += (t[a] * t[b] * t[c]
                        * p[:, a:a + D, b:b + H, c:c + W])
    off = 0 if boundary == "periodic" else 1
    cd = transfer.coarse_extent(D, boundary)
    ch = transfer.coarse_extent(H, boundary)
    cw = transfer.coarse_extent(W, boundary)
    return out[:, off::2, off::2, off::2][:, :cd, :ch, :cw]


def _np_prolong3(c, fine_dhw, boundary):
    """Independent trilinear prolongation: per-fine-cell neighbor means
    with explicit ghost reads (wrap or zero)."""
    F = c.shape[0]
    m = c.shape[1:]
    nd, nh, nw = fine_dhw
    out = np.zeros((F, nd, nh, nw))

    def cv(f, i, j, k):
        if boundary == "periodic":
            return c[f, i % m[0], j % m[1], k % m[2]]
        if 0 <= i < m[0] and 0 <= j < m[1] and 0 <= k < m[2]:
            return c[f, i, j, k]
        return 0.0

    def idxs(fi):
        q, r = divmod(fi if boundary == "periodic" else fi - 1, 2)
        return [q] if r == 0 else [q, q + 1]

    for f in range(F):
        for fi in range(nd):
            for fj in range(nh):
                for fk in range(nw):
                    out[f, fi, fj, fk] = np.mean([
                        np.mean([
                            np.mean([cv(f, i, j, k) for k in idxs(fk)])
                            for j in idxs(fj)])
                        for i in idxs(fi)])
    return out


def _run_transfer3(form_name, x, grid, depth, valid_hw, block_hw,
                   boundary):
    mesh = _mesh(grid)
    build = kernel_forms.resolve(3, form_name, boundary).build
    fn = jax.jit(shard_map(
        build(grid, depth, valid_hw, block_hw, boundary),
        mesh=mesh, in_specs=SPEC, out_specs=SPEC, check_vma=False))
    xs = jax.device_put(jnp.asarray(x, jnp.float32),
                        driver.volume_sharding(mesh))
    return np.asarray(fn(xs))


@pytest.mark.parametrize("boundary", BOUNDARIES)
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)])
def test_restrict_fw3_matches_numpy(boundary, grid):
    rng = np.random.default_rng(19)
    D, H, W = 8, 16, 8
    vol = _vol(rng, D, H, W)
    R, C = grid
    got = _run_transfer3("restrict_fw", vol, grid, D, (H, W),
                         (H // R, W // C), boundary)
    want = _np_restrict3(vol, boundary)
    cd, ch, cw = want.shape[1:]
    np.testing.assert_allclose(got[:, :cd, :ch, :cw], want,
                               rtol=0, atol=1e-5)
    # Beyond the coarse extents (the odd-centered zero tails, including
    # the resident-depth plane no rank-2 mask covers) everything is 0.
    assert not got[:, cd:].any()
    assert not got[:, :, ch:].any()
    assert not got[:, :, :, cw:].any()


@pytest.mark.parametrize("boundary", BOUNDARIES)
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)])
def test_prolong_trilinear_matches_numpy(boundary, grid):
    rng = np.random.default_rng(23)
    D, H, W = 8, 16, 8
    R, C = grid
    coarse = rng.random(
        (VOLUME_FIELDS, D // 2, H // 2, W // 2), dtype=np.float32)
    if boundary == "zero":
        # A real coarse field obeys the masking invariant: zero beyond
        # the odd-centered coarse extents.
        coarse[:, transfer.coarse_extent(D, boundary):] = 0.0
        coarse[:, :, transfer.coarse_extent(H, boundary):] = 0.0
        coarse[:, :, :, transfer.coarse_extent(W, boundary):] = 0.0
    got = _run_transfer3("prolong_trilinear", coarse, grid, D, (H, W),
                         (H // R, W // C), boundary)
    want = _np_prolong3(coarse, (D, H, W), boundary)
    assert got.shape == (VOLUME_FIELDS, D, H, W)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)


def test_transfer3_rejects_odd_geometry():
    with pytest.raises(ValueError, match="even depth"):
        transfer.build_restrict_fw3((1, 1), 7, (8, 8), (8, 8))
    with pytest.raises(ValueError, match="even per-device blocks"):
        transfer.build_prolong_trilinear((1, 1), 8, (9, 8), (9, 8))


# ------------------------------------------------- cost model arithmetic


def test_volume_cost_model_taps_mirror_forms_and_price_scales():
    # Drift guard: the jax-free tap table covers exactly the registered
    # form names (their radii table too).
    assert set(costmodel.VOLUME_FORM_TAPS) == set(VOLUME_FORMS)
    assert set(VOLUME_RADII) == set(VOLUME_FORMS)
    assert costmodel.volume_bytes_per_cell_iter("f32", fields=2) > 0
    hw = costmodel.CPU_HOST
    kw = dict(grid=(2, 4), block_hw=(12, 10), depth=6, fuse=1, hw=hw)
    s7 = costmodel.predict_volume_seconds_per_cell_iter(
        radius=VOLUME_RADII["fd7"], name="fd7", **kw)
    s25 = costmodel.predict_volume_seconds_per_cell_iter(
        radius=VOLUME_RADII["fd25"], name="fd25", **kw)
    assert s25 > s7 > 0
    # A 1x1 grid pays no exchange term.
    solo = costmodel.predict_volume_seconds_per_cell_iter(
        grid=(1, 1), block_hw=(24, 40), depth=6, radius=1, fuse=1,
        name="fd7", hw=hw)
    assert solo < s7


def test_volume_face_bytes_attribution():
    """±D faces are a local pad: only ±H/±W slabs cross links, each at
    an effective channel count fields*(depth + 2*r*fuse)."""
    grid, block, depth, r = (2, 4), (12, 10), 6, 1
    got = attribution.volume_face_bytes_per_round(
        grid, block, depth, r, fuse=1, fields=2)
    want = attribution.halo_bytes_per_round(
        grid, block, r, 1, 2 * (depth + 2 * r), "f32", "zero")
    assert got == want
    # Deeper fused ghosts widen the slab channel count strictly.
    fused = attribution.volume_face_bytes_per_round(
        grid, block, depth, r, fuse=3, fields=2)
    assert sum(fused.values()) > sum(got.values())


# --------------------------------------------------------------- CLI arm


def test_cli_rank3_physics_end_to_end(tmp_path, capsys):
    """wave and grayscott through the real ``run --rank 3`` arm: raw
    f32 (2, D, H, W) in, oracle-checked raw f32 out; a fixed-count run
    and a converge run (the ISSUE's CLI acceptance drill)."""
    from parallel_convolution_tpu import cli

    rng = np.random.default_rng(11)
    vol = rng.random((2, 4, 16, 16), dtype=np.float32)
    src = str(tmp_path / "vol.raw")
    vol.tofile(src)

    out = str(tmp_path / "wave.raw")
    rc = cli.main(["run", src, "16", "16", "5", "grey", "-o", out,
                   "--rank", "3", "--depth", "4", "--filter", "wave",
                   "--boundary", "periodic", "--mesh", "2x2"])
    assert rc == 0
    got = np.fromfile(out, np.float32).reshape(vol.shape)
    want = oracle3.run_oracle(vol, "wave", 5, "periodic")
    np.testing.assert_allclose(got, want, rtol=0, atol=2e-4)
    assert "5 x wave" in capsys.readouterr().out

    # Gray-Scott needs the classic bounded start (U=1, V=0, perturbed
    # blob): raw amplitude-1 noise sits outside the reaction's stable
    # basin at dt=1 and blows up within a few steps.
    gs = np.zeros_like(vol)
    gs[0] = 1.0
    gs[0, :, 6:10, 6:10] = 0.5
    gs[1, :, 6:10, 6:10] = 0.25
    gs += 0.01 * rng.random(gs.shape, dtype=np.float32)
    gsrc = str(tmp_path / "gs_in.raw")
    gs.tofile(gsrc)
    out2 = str(tmp_path / "gs.raw")
    rc = cli.main(["run", gsrc, "16", "16", "8", "grey", "-o", out2,
                   "--rank", "3", "--depth", "4", "--filter",
                   "grayscott", "--boundary", "periodic", "--mesh",
                   "2x2", "--converge", "0.0", "--check-every", "4"])
    assert rc == 0
    got2 = np.fromfile(out2, np.float32).reshape(vol.shape)
    want2 = oracle3.run_oracle(gs, "grayscott", 8, "periodic")
    np.testing.assert_allclose(got2, want2, rtol=0, atol=2e-4)
    assert "converged after 8 iters" in capsys.readouterr().out


def test_cli_rank3_rejections_are_typed_exits(tmp_path, capsys):
    """The rank-3 CLI guard rails exit 2 with a reason, never a trace."""
    from parallel_convolution_tpu import cli

    src = str(tmp_path / "vol.raw")
    np.random.default_rng(0).random((2, 4, 8, 8),
                                    dtype=np.float32).tofile(src)
    base = ["run", src, "8", "8", "2", "grey", "-o",
            str(tmp_path / "o.raw"), "--rank", "3"]
    assert cli.main(base) == 2                       # missing --depth
    assert "--depth" in capsys.readouterr().err
    assert cli.main([*base, "--depth", "4",
                     "--filter", "blur3"]) == 2      # rank-2 form
    assert "rank-3 form" in capsys.readouterr().err
    assert cli.main([*base, "--depth", "4", "--filter", "fd7",
                     "--solver", "multigrid"]) == 2  # rank-2-only solver
    assert "jacobi only" in capsys.readouterr().err
    assert cli.main([*base, "--depth", "8",
                     "--filter", "fd7"]) == 2        # size mismatch
    assert "expected" in capsys.readouterr().err
