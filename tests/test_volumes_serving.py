"""Rank-3 volumes through the serving plane (round 23).

The subsystem's serving contract, end to end:

1. BATCH — a typed volume request (JSON ``volume_b64`` and the r20
   binary tensor-frame wire) round-trips through admission → pricing →
   micro-batching → the warm engine, matches the independent float64
   oracle, is byte-identical across the two wires, and hits the
   content-addressed result cache on resubmission.
2. CONVERGE — wave and Gray–Scott stream best-so-far snapshots whose
   final row matches the oracle at the same iteration count; rows carry
   the jacobi solver stamp with ``work_units == iters`` (a volume's
   fine-grid work IS its iteration count).
3. FAILOVER — the soak-style mid-stream drills: (a) a stream interrupted
   after its first snapshot resumes from that row's resume token to a
   byte-identical final; (b) a stream caught by the r10 mesh ladder
   sheds typed-retryable and the retry completes on the NEW grid with
   byte-identical finals (rank-3 forms are bitwise mesh-invariant).
4. TYPED INVALIDS — rank-2 filter names, wrong dtype/shape, image+volume
   both set, rank-2-only solvers, and periodic indivisibility all fail
   admission as ``invalid``, never inside a trace.
"""

from __future__ import annotations

import base64

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.serving import frames as frames_mod
from parallel_convolution_tpu.serving import jobs
from parallel_convolution_tpu.serving.frontend import InProcessClient
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Rejected, Request, Snapshot,
)
from parallel_convolution_tpu.volumes import oracle3


def _mesh(shape=(2, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _svc(**kw):
    kw.setdefault("max_delay_s", 0.002)
    return ConvolutionService(kw.pop("mesh", _mesh()), **kw)


def _vol(seed=7, d=4, h=16, w=16):
    return np.random.default_rng(seed).random(
        (2, d, h, w), dtype=np.float32)


def _body(vol, **kw):
    b = {"rows": vol.shape[2], "cols": vol.shape[3],
         "depth": vol.shape[1], "mode": "volume",
         "volume_b64": base64.b64encode(vol.tobytes()).decode()}
    b.update(kw)
    return b


def _decode_final(row, shape=None):
    out = np.frombuffer(base64.b64decode(row["image_b64"]), np.float32)
    return out.reshape(shape if shape is not None else row["image_shape"])


# ------------------------------------------------------------------ batch


def test_volume_batch_both_wires_byte_identical_and_cached():
    svc = _svc(cache=True)
    try:
        client = InProcessClient(svc)
        vol = _vol(1)
        body = _body(vol, filter="fd7", iters=5, boundary="zero")
        status, resp = client.request(dict(body))
        assert status == 200, resp
        out = _decode_final(resp, vol.shape)
        want = oracle3.run_oracle(vol, "fd7", 5, "zero")
        np.testing.assert_allclose(out, want, rtol=0, atol=2e-5)
        assert resp["plan_key"].startswith("vol|fd7|4x16x16|zero|")

        # the r20 binary frame wire: same request, same BYTES back
        raw = frames_mod.encode_envelope(
            {k: v for k, v in body.items() if k != "volume_b64"},
            {"volume": vol})
        status, data = client.request_frames(raw)
        assert status == 200
        hdr, arrs = frames_mod.decode_envelope(data)
        assert hdr["ok"], hdr
        framed = np.asarray(arrs["image"])
        assert framed.dtype == np.float32
        assert framed.tobytes() == out.tobytes()

        # content-addressed cache: identical resubmission is a hit
        status, resp2 = client.request(dict(body))
        assert status == 200 and resp2["cache"] == "hit"
        assert resp2["image_b64"] == resp["image_b64"]
    finally:
        svc.close()


def test_volume_batch_fd25_smooth_form_serves():
    svc = _svc()
    try:
        vol = _vol(2)   # blocks 8x8 >= fd25's radius 4 on 2x2
        status, resp = InProcessClient(svc).request(
            _body(vol, filter="fd25", iters=2, boundary="zero"))
        assert status == 200, resp
        want = oracle3.run_oracle(vol, "fd25", 2, "zero")
        np.testing.assert_allclose(_decode_final(resp, vol.shape), want,
                                   rtol=0, atol=2e-5)
    finally:
        svc.close()


# --------------------------------------------------------------- converge


@pytest.mark.parametrize("name", ["wave", "grayscott"])
def test_physics_converge_stream_matches_oracle(name):
    svc = _svc()
    try:
        vol = _vol(3)
        rows = list(svc.submit_progressive(
            Request(volume=vol, filter_name=name, boundary="periodic"),
            tol=0.0, max_iters=12, check_every=4))
        assert all(isinstance(r, Snapshot) for r in rows)
        final = rows[-1]
        assert final.final and not final.converged and final.iters == 12
        assert final.image.dtype == np.float32
        # a volume's solver-comparable work IS its iteration count
        for r in rows:
            assert r.solver == "jacobi"
            assert r.work_units == float(r.iters)
        want = oracle3.run_oracle(vol, name, 12, "periodic")
        np.testing.assert_allclose(final.image, want, rtol=0, atol=2e-4)
    finally:
        svc.close()


def test_volume_converge_resume_token_byte_identical_final():
    # Failover drill (a): interrupt after the first snapshot, carry its
    # resume token into a fresh request, land on the same final bytes.
    svc = _svc()
    try:
        client = InProcessClient(svc)
        vol = _vol(4)
        body = _body(vol, filter="wave", boundary="periodic",
                     tol=0.0, max_iters=12, check_every=4,
                     resume_state=True)
        status, rows = client.converge(dict(body))
        assert status == 200
        rows = list(rows)
        final = [r for r in rows if r.get("kind") == "final"][0]

        tok = jobs.token_from_row(rows[0])
        assert tok is not None and tok["iters"] == 4
        body2 = dict(body)
        body2["resume"] = tok
        status, rows2 = client.converge(body2)
        assert status == 200
        fin2 = [r for r in rows2 if r.get("kind") == "final"][0]
        assert fin2["image_b64"] == final["image_b64"]
        assert fin2["iters"] == final["iters"] == 12
    finally:
        svc.close()


def test_volume_converge_survives_reshape_with_typed_shed():
    # Failover drill (b): the r10 mesh ladder interrupts a rank-3
    # stream; the shed is typed retryable, and the retry's final on the
    # NEW grid is byte-identical (bitwise mesh invariance, served).
    svc = _svc()
    try:
        vol = _vol(5)
        req = Request(volume=vol, filter_name="fd7", boundary="zero")
        want = list(svc.submit_progressive(
            req, tol=0.0, max_iters=12, check_every=4))[-1]
        assert isinstance(want, Snapshot) and want.final

        stream = iter(svc.submit_progressive(
            req, tol=0.0, max_iters=12, check_every=4))
        first = next(stream)
        assert isinstance(first, Snapshot) and first.iters == 4
        info = svc.reshape("1x2")
        assert info["grid"] == (1, 2)
        tail = list(stream)
        assert tail, "interrupted stream must end with a typed row"
        shed = tail[-1]
        assert isinstance(shed, Rejected), shed
        assert shed.reason == "resharding" and shed.retryable
        assert all(isinstance(r, Snapshot) for r in tail[:-1])

        final = list(svc.submit_progressive(
            req, tol=0.0, max_iters=12, check_every=4))[-1]
        assert isinstance(final, Snapshot) and final.final
        assert final.effective_grid == "1x2"
        assert final.iters == want.iters
        assert final.image.tobytes() == want.image.tobytes()
    finally:
        svc.close()


# ---------------------------------------------------------- typed invalids


def test_volume_invalid_requests_are_typed():
    svc = _svc()
    try:
        vol = _vol(6)
        cases = [
            Request(volume=vol, filter_name="blur3"),        # rank-2 form
            Request(volume=vol.astype(np.float64)),          # dtype
            Request(volume=vol[0]),                          # rank
            Request(volume=vol[:1]),                         # field count
            Request(volume=vol, solver="multigrid"),         # rank-2 only
            Request(volume=vol,
                    image=np.zeros((8, 8), np.uint8)),       # both set
            Request(volume=_vol(6, h=15), boundary="periodic"),  # 15 % 2
        ]
        for req in cases:
            r = svc.submit(req)
            assert isinstance(r, Rejected) and r.reason == "invalid", req
        # ... and the wire surface agrees (no depth -> typed 400)
        client = InProcessClient(svc)
        body = _body(vol, filter="fd7")
        del body["depth"]
        status, resp = client.request(body)
        assert status == 400 and resp["rejected"] == "invalid"
    finally:
        svc.close()
