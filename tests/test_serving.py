"""Serving layer: warm engine, micro-batcher, admission control, frontend.

The round-8 acceptance properties (ISSUE 3), all on the 8-virtual-device
CPU mesh:

* batched responses are byte-identical to sequential single-request runs
  AND to the serial oracle, for every backend the CPU mesh supports
  (test_batched_bitexact_vs_sequential_and_oracle);
* a second request on a warm key performs zero recompilation — the
  engine's compile counter is flat and its hit counter moves
  (test_second_request_warm_key_zero_recompile);
* queue overflow yields a typed, counted ``Rejected`` — never an
  exception and never a hang (test_queue_overflow_typed_rejection).
"""

from __future__ import annotations

import base64
import threading
import time

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.resilience import degrade, faults
from parallel_convolution_tpu.resilience.retry import RetryPolicy
from parallel_convolution_tpu.serving.batcher import MicroBatcher
from parallel_convolution_tpu.serving.engine import WarmEngine
from parallel_convolution_tpu.serving.frontend import (
    InProcessClient, make_http_server,
)
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Rejected, Request, Response,
)
from parallel_convolution_tpu.utils import imageio, tracing
from parallel_convolution_tpu.utils.config import BACKENDS


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


def _mesh(shape=(2, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _service(**kw):
    kw.setdefault("mesh", _mesh())
    kw.setdefault("max_delay_s", 0.02)
    kw.setdefault("retry_policy",
                  RetryPolicy(max_attempts=3, base_delay=0.01,
                              max_delay=0.05))
    return ConvolutionService(kw.pop("mesh"), **kw)


# ------------------------------------------------------------- PhaseTimer


def test_phase_timer_nested_paths_and_to_row():
    t = tracing.PhaseTimer()
    with t.phase("serve"):
        with t.phase("device"):
            pass
        with t.phase("device"):
            pass
    with t.phase("queue"):
        pass
    assert set(t.walls) == {"serve", "serve/device", "queue"}
    assert t.counts["serve/device"] == 2
    row = t.to_row()
    assert set(row) == {"phase_serve_s", "phase_serve_device_s",
                        "phase_queue_s"}
    assert row["phase_serve_s"] >= row["phase_serve_device_s"] >= 0.0
    assert t.wall("serve") >= t.wall("serve/device")
    assert t.wall("never_entered") == 0.0


def test_phase_timer_report_counts_top_level_only():
    t = tracing.PhaseTimer()
    with t.phase("outer"):
        with t.phase("inner"):
            time.sleep(0.01)
    rep = t.report()
    # Nested walls must not double-count into the total.
    assert rep["total_s"] == round(t.walls["outer"], 4)
    assert "outer/inner" in rep["phases"]


# ------------------------------------------------------------ MicroBatcher


class _StubExec:
    """Records flushed batches; completes every slot with its payload."""

    def __init__(self, delay=0.0):
        self.batches = []
        self.delay = delay

    def __call__(self, key, items):
        if self.delay:
            time.sleep(self.delay)
        self.batches.append((key, [it.payload for it in items]))
        for it in items:
            it.slot.set(("done", key, it.payload))


def test_batcher_deadline_flush_single_request():
    ex = _StubExec()
    b = MicroBatcher(ex, max_batch=8, max_delay_s=0.03, max_queue=4)
    t0 = time.monotonic()
    slot = b.try_submit("k", 1)
    assert slot is not None
    assert slot.result(5.0) == ("done", "k", 1)
    # A lone request flushes on the deadline, not on a full batch.
    assert time.monotonic() - t0 < 2.0
    assert ex.batches == [("k", [1])]
    b.close()


def test_batcher_coalesces_same_key_up_to_max_batch():
    ex = _StubExec()
    b = MicroBatcher(ex, max_batch=3, max_delay_s=0.05, max_queue=16,
                     start=False)
    slots = [b.try_submit("k", i) for i in range(5)]
    b.start()
    for s in slots:
        assert s.result(5.0) is not None
    sizes = [len(p) for k, p in ex.batches]
    assert sizes == [3, 2]                     # cap respected, order kept
    assert [p for _, p in ex.batches] == [[0, 1, 2], [3, 4]]
    b.close()


def test_batcher_mixed_keys_never_cobatched():
    ex = _StubExec()
    b = MicroBatcher(ex, max_batch=8, max_delay_s=0.02, max_queue=16,
                     start=False)
    for key, payload in [("a", 1), ("b", 2), ("a", 3), ("b", 4)]:
        assert b.try_submit(key, payload) is not None
    b.start()
    deadline = time.monotonic() + 5.0
    while len(ex.batches) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sorted((k, tuple(p)) for k, p in ex.batches) == [
        ("a", (1, 3)), ("b", (2, 4))]          # same-key only, both served
    b.close()


def test_batcher_queue_full_refused_and_counted():
    ex = _StubExec()
    b = MicroBatcher(ex, max_batch=2, max_delay_s=0.01, max_queue=2,
                     start=False)
    assert b.try_submit("k", 1) is not None
    assert b.try_submit("k", 2) is not None
    assert b.try_submit("k", 3) is None        # typed refusal, no exception
    assert b.stats["refused"] == 1
    b.start()
    b.close(drain=True)
    assert b.stats["flushed_items"] == 2       # the admitted two completed


def test_batcher_close_refuses_new_work():
    b = MicroBatcher(_StubExec(), max_queue=4)
    b.close()
    assert b.try_submit("k", 1) is None


# -------------------------------------------------------------- WarmEngine


def _img(h=24, w=36, mode="grey", seed=1):
    return imageio.generate_test_image(h, w, mode, seed=seed)


def _planar(img):
    return imageio.interleaved_to_planar(img).astype(np.float32)


def test_engine_warm_key_caches_executable():
    eng = WarmEngine(_mesh(), fallback=False)
    key = eng.key_for((1, 24, 36), filter_name="blur3", iters=2)
    x = _planar(_img())[None]
    out1, info1 = eng.run_batch(key, x)
    compiles = eng.stats["compiles"]
    out2, info2 = eng.run_batch(key, x)
    assert eng.stats["compiles"] == compiles   # zero recompilation
    assert eng.stats["hits"] >= 1
    np.testing.assert_array_equal(out1, out2)
    assert info2["effective_backend"] == "shifted"
    assert set(info2["phases"]) == {"compile", "copy_in", "device",
                                    "copy_out"}


def test_engine_lru_eviction_and_recompile():
    eng = WarmEngine(_mesh(), capacity=1, fallback=False)
    k1 = eng.key_for((1, 24, 36), filter_name="blur3", iters=1)
    k2 = eng.key_for((1, 24, 36), filter_name="box3", iters=1)
    eng.entry(k1)
    eng.entry(k2)                              # evicts k1
    assert eng.stats["evictions"] == 1
    eng.entry(k1)                              # cold again
    assert eng.stats["compiles"] == 3
    assert [r["filter"] for r in eng.snapshot()["resident"]] == ["blur3"]


def test_engine_single_flight_cold_key_compiles_once():
    eng = WarmEngine(_mesh(), fallback=False)
    key = eng.key_for((1, 26, 34), filter_name="gaussian5", iters=1)
    barrier = threading.Barrier(4)
    errors = []

    def worker():
        try:
            barrier.wait(timeout=10)
            eng.entry(key)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    assert eng.stats["compiles"] == 1          # one leader compiled
    assert eng.stats["misses"] == 1
    assert eng.stats["hits"] + eng.stats["single_flight_waits"] == 3


def test_engine_key_validation_is_terminal():
    eng = WarmEngine(_mesh(), fallback=False)
    with pytest.raises(ValueError):
        eng.key_for((1, 24, 36), backend="nope").validate()
    with pytest.raises(ValueError):
        eng.key_for((1, 24, 36), storage="u8", quantize=False).validate()
    key = eng.key_for((1, 24, 36))
    with pytest.raises(ValueError):
        eng.run_batch(key, np.zeros((1, 1, 8, 8), np.float32))


def test_engine_warmup_precompiles_declared_configs():
    svc = _service()
    effective = svc.warmup([{"rows": 24, "cols": 36, "filter": "blur3",
                             "iters": 2}])
    assert effective == ["shifted"]
    compiles = svc.engine.stats["compiles"]
    resp = svc.submit(Request(image=_img(), iters=2), timeout=60)
    assert isinstance(resp, Response)
    assert svc.engine.stats["compiles"] == compiles   # served fully warm
    svc.close()


# ------------------------------------------------- service: bit-exactness


def _supported(backend, mesh, filt, block_hw):
    """Does this backend compile+run on the CPU mesh?  (Probe verdict —
    the same definition resolve_backend uses.)"""
    try:
        degrade.probe_backend(mesh, filt, backend, block_hw=block_hw)
        return True
    except Exception:  # noqa: BLE001 — unsupported here, whatever the class
        return False


@pytest.mark.parametrize("backend", BACKENDS)
def test_batched_bitexact_vs_sequential_and_oracle(backend):
    mesh = _mesh()
    filt = filters.get_filter("blur3")
    img = _img(32, 48)
    if not _supported(backend, mesh, filt, (16, 24)):
        pytest.skip(f"{backend} does not run on this CPU mesh/jax")
    want = oracle.run_serial_u8(img, filt, 2)

    svc = _service(mesh=mesh, max_batch=4, max_delay_s=0.25, fallback=False)
    # Sequential oracle runs: one at a time, each its own batch.
    seq = svc.submit(Request(image=img, iters=2, backend=backend),
                     timeout=120)
    assert isinstance(seq, Response), seq
    assert seq.batch_size == 1
    # Concurrent same-key burst: must co-batch, and match byte-for-byte.
    results = [None] * 4

    def one(i):
        results[i] = svc.submit(Request(image=img, iters=2, backend=backend),
                                timeout=120)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    for r in results:
        assert isinstance(r, Response), r
        assert r.effective_backend == backend
        np.testing.assert_array_equal(r.image, seq.image)
        np.testing.assert_array_equal(r.image, want)
    assert max(r.batch_size for r in results) > 1   # batching really happened
    svc.close()


def test_rgb_roundtrip_matches_oracle():
    img = _img(24, 30, mode="rgb", seed=7)
    want = oracle.run_serial_u8(img, filters.get_filter("sharpen3"), 2)
    svc = _service()
    resp = svc.submit(Request(image=img, filter_name="sharpen3", iters=2),
                      timeout=120)
    assert isinstance(resp, Response)
    assert resp.image.shape == img.shape
    np.testing.assert_array_equal(resp.image, want)
    svc.close()


def test_second_request_warm_key_zero_recompile():
    svc = _service()
    img = _img()
    r1 = svc.submit(Request(image=img, iters=2), timeout=120)
    assert isinstance(r1, Response)
    compiles = svc.engine.stats["compiles"]
    hits = svc.engine.stats["hits"]
    r2 = svc.submit(Request(image=img, iters=2), timeout=120)
    assert isinstance(r2, Response)
    assert svc.engine.stats["compiles"] == compiles   # ZERO recompilation
    assert svc.engine.stats["hits"] > hits            # the cache served it
    assert r2.effective_backend == "shifted"          # stamped per response
    np.testing.assert_array_equal(r1.image, r2.image)
    assert r2.phases["compile"] < 0.05                # warm path, no trace
    svc.close()


# --------------------------------------------- service: admission control


def test_queue_overflow_typed_rejection():
    svc = _service(max_queue=3, start=False)          # worker not running
    img = _img()
    slots = [svc.submit(Request(image=img, iters=1), wait=False)
             for _ in range(3)]
    shed = svc.submit(Request(image=img, iters=1), timeout=5)
    assert isinstance(shed, Rejected)
    assert shed.reason == "queue_full"
    assert svc.stats["rejected_queue_full"] == 1
    svc.batcher.start()                               # drain the admitted 3
    for s in slots:
        r = s.result(120)
        assert isinstance(r, Response)
    svc.close()


def test_tight_deadline_on_idle_service_is_served_not_starved():
    # deadline_s < max_delay_s must flush immediately, not wait out the
    # batching window and then shed its own request (review finding).
    svc = _service(max_delay_s=0.5)
    svc.warmup([{"rows": 24, "cols": 36, "filter": "blur3", "iters": 1}])
    t0 = time.monotonic()
    r = svc.submit(Request(image=_img(), iters=1, deadline_s=0.2),
                   timeout=60)
    assert isinstance(r, Response), r
    assert time.monotonic() - t0 < 0.45    # did not sit out max_delay_s
    svc.close()


def test_client_wait_timeout_is_distinct_typed_reason():
    svc = _service(start=False)            # worker stopped: nothing answers
    r = svc.submit(Request(image=_img(), iters=1), timeout=0.05)
    assert isinstance(r, Rejected)
    assert r.reason == "timeout"           # not conflated with "deadline"
    assert svc.stats["client_timeouts"] == 1
    assert svc.stats["rejected_deadline"] == 0
    svc.batcher.close(drain=False)


def test_phase_timer_stack_survives_raising_body():
    t = tracing.PhaseTimer()
    with pytest.raises(RuntimeError):
        with t.phase("boom"):
            raise RuntimeError("injected")
    with t.phase("after"):
        pass
    assert set(t.walls) == {"boom", "after"}   # not "boom/after"


def test_wire_decode_null_knob_is_typed_invalid():
    # int(None) used to escape as TypeError past the 400 path (review).
    svc = _service(start=False)
    client = InProcessClient(svc)
    status, resp = client.request(_wire_body(_img(), iters=None))
    assert status == 400 and resp["rejected"] == "invalid"
    status, resp = client.request(_wire_body(_img(), deadline_ms=[5]))
    assert status == 400 and resp["rejected"] == "invalid"
    svc.batcher.close(drain=False)


def test_expired_deadline_typed_rejection():
    svc = _service(start=False)
    slot = svc.submit(Request(image=_img(), iters=1, deadline_s=0.01),
                      wait=False)
    time.sleep(0.05)
    svc.batcher.start()
    r = slot.result(60)
    assert isinstance(r, Rejected)
    assert r.reason == "deadline"
    assert svc.stats["rejected_deadline"] == 1
    svc.close()


def test_invalid_requests_typed_rejection():
    svc = _service(start=False)
    bad_filter = svc.submit(Request(image=_img(), filter_name="nope"))
    assert isinstance(bad_filter, Rejected) and bad_filter.reason == "invalid"
    bad_dtype = svc.submit(
        Request(image=np.zeros((8, 8), np.float32)))
    assert isinstance(bad_dtype, Rejected) and bad_dtype.reason == "invalid"
    big_fuse = svc.submit(Request(image=_img(8, 8), iters=64, fuse=64))
    assert isinstance(big_fuse, Rejected) and big_fuse.reason == "invalid"
    assert svc.stats["rejected_invalid"] == 3
    svc.close()


def test_mixed_key_requests_served_in_separate_batches():
    svc = _service(max_batch=8, max_delay_s=0.2)
    img = _img()
    want_blur = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)
    want_box = oracle.run_serial_u8(img, filters.get_filter("box3"), 1)
    out = {}

    def one(name):
        out[name] = svc.submit(Request(image=img, filter_name=name),
                               timeout=120)

    threads = [threading.Thread(target=one, args=(n,))
               for n in ("blur3", "box3")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    for name, want in (("blur3", want_blur), ("box3", want_box)):
        assert isinstance(out[name], Response)
        assert out[name].batch_size == 1       # different keys: never merged
        np.testing.assert_array_equal(out[name].image, want)
    svc.close()


# ------------------------------------------------- service: resilience


def test_compile_fault_walks_degradation_ladder():
    img = _img(26, 38, seed=5)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 3)
    with faults.injected("backend_compile:1"):
        svc = _service(fallback=True)
        resp = svc.submit(Request(image=img, iters=3, backend="pallas"),
                          timeout=120)
        assert isinstance(resp, Response), resp
        # The pallas probe ate the injected fault; the ladder walked to the
        # normative tier and the response says so.
        assert resp.backend == "pallas"
        assert resp.effective_backend == "shifted"
        np.testing.assert_array_equal(resp.image, want)
        svc.close()


def test_transient_engine_fault_healed_by_retry():
    img = _img(28, 44, seed=6)
    want = oracle.run_serial_u8(img, filters.get_filter("sharpen3"), 2)
    with faults.injected("halo_exchange:1"):
        svc = _service(fallback=False)         # no probe: retry must heal it
        resp = svc.submit(Request(image=img, filter_name="sharpen3",
                                  iters=2), timeout=120)
        assert isinstance(resp, Response), resp
        assert svc.stats["retries"] >= 1
        assert resp.effective_backend == "shifted"
        np.testing.assert_array_equal(resp.image, want)
        svc.close()


def test_exhausted_transient_faults_become_typed_error():
    with faults.injected("backend_compile:*"):
        svc = _service(fallback=False,
                       retry_policy=RetryPolicy(max_attempts=2,
                                                base_delay=0.01,
                                                max_delay=0.02))
        resp = svc.submit(Request(image=_img(30, 42, seed=9), iters=1),
                          timeout=120)
        assert isinstance(resp, Rejected)
        assert resp.reason == "error"
        assert svc.stats["rejected_error"] == 1
        svc.close()


# ----------------------------------------------------------- frontend


def _wire_body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1],
        "mode": "rgb" if img.ndim == 3 else "grey"}
    body.update(kw)
    return body


def test_inprocess_client_roundtrip_and_rejection_codec():
    svc = _service()
    client = InProcessClient(svc)
    img = _img()
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    status, resp = client.request(_wire_body(img, iters=2), timeout=120)
    assert status == 200 and resp["ok"]
    got = np.frombuffer(base64.b64decode(resp["image_b64"]),
                        np.uint8).reshape(img.shape)
    np.testing.assert_array_equal(got, want)
    assert resp["effective_backend"] == "shifted"
    assert resp["phases"]["total"] >= resp["phases"]["device"]

    status, resp = client.request({"rows": 8})          # malformed body
    assert status == 400 and resp["rejected"] == "invalid"
    status, resp = client.request(_wire_body(img, filter="nope"))
    assert status == 400 and resp["rejected"] == "invalid"
    status, health = client.healthz()
    assert status == 200 and health["ok"]
    assert health["service"]["completed"] >= 1
    svc.close()


def test_loadgen_inprocess_emits_schema_valid_row(tmp_path):
    """The acceptance row: scripts/loadgen.py against the CPU-mesh service
    emits p50/p95/p99 + phase breakdown + effective_backend, oracle-checked,
    with zero non-rejected failures (exit 0) — and (round 13) every
    ``--trace-out`` per-request row carries the SERVER-assigned trace_id
    so client- and server-side records of one request join offline."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    from parallel_convolution_tpu.utils.platform import child_env_cpu

    script = Path(__file__).resolve().parents[1] / "scripts" / "loadgen.py"
    trace_out = tmp_path / "lg_trace.jsonl"
    env = child_env_cpu(8)
    env["PCTPU_OBS"] = "1"
    p = subprocess.run(
        [sys.executable, str(script), "--in-process", "--n", "8",
         "--concurrency", "2", "--rows", "24", "--cols", "36",
         "--iters", "2", "--mesh", "2x2", "--check",
         "--trace-out", str(trace_out)],
        capture_output=True, text=True, timeout=300, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    row = json.loads(p.stdout.strip().splitlines()[-1])
    for field in ("workload", "backend", "effective_backend", "completed",
                  "rejected", "non_rejected_failures", "wall_s", "p50_ms",
                  "p95_ms", "p99_ms", "gpixels_per_s", "phases_ms",
                  "platform", "mesh", "plan_key"):
        assert field in row, f"missing {field!r} in {sorted(row)}"
    assert row["completed"] == 8
    assert row["non_rejected_failures"] == 0
    assert row["oracle_mismatches"] == 0
    assert row["effective_backend"] == "shifted"
    assert row["platform"] == "cpu" and row["mesh"] == "2x2"
    assert row["plan_key"]            # perf_gate.py's history key
    assert set(row["phases_ms"]) == {"queue", "compile", "device",
                                     "copy_in", "copy_out"}
    assert row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]
    lines = [json.loads(l) for l in trace_out.read_text().splitlines()]
    assert len(lines) == 8
    assert all(ln["trace_id"] for ln in lines)
    assert len({ln["trace_id"] for ln in lines}) == 8   # per-request ids


def test_http_frontend_over_loopback():
    import socket
    import urllib.request

    try:
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        probe.close()
    except OSError:
        pytest.skip("loopback sockets unavailable in this sandbox")
    svc = _service()
    server = make_http_server(svc, port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        img = _img()
        want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/convolve",
            data=__import__("json").dumps(_wire_body(img)).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            payload = __import__("json").loads(resp.read())
        assert payload["ok"]
        got = np.frombuffer(base64.b64decode(payload["image_b64"]),
                            np.uint8).reshape(img.shape)
        np.testing.assert_array_equal(got, want)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            assert __import__("json").loads(resp.read())["ok"]
        # keep-alive regression: a 404'd POST must DRAIN its body —
        # under HTTP/1.1 an unread body would be parsed as the next
        # request line, corrupting a valid request reusing the
        # connection.
        import http.client
        json_mod = __import__("json")
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            conn.request("POST", "/nope",
                         body=json_mod.dumps(_wire_body(img)).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 404
            resp.read()
            conn.request("POST", "/v1/convolve",
                         body=json_mod.dumps(_wire_body(img)).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json_mod.loads(resp.read())["ok"]
        finally:
            conn.close()
    finally:
        server.shutdown()
        server.server_close()
        svc.close()
