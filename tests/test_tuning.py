"""Autotuning subsystem tests (tuning/: costmodel, search, plans, auto).

Covers the ISSUE-9 acceptance set:

* plan-key stability across dict construction order;
* atomic plan-file writes + corrupt/wrong-schema fallback-to-empty;
* cost-model monotonicity (more fuse => fewer predicted bytes/px until
  the rim-recompute overhead dominates);
* ``backend="auto"`` on the 2x4 CPU mesh resolving deterministically
  and byte-identical to the explicitly-named backend AND the oracle —
  with no plan file (predicted), with an exact plan (measured), with a
  neighboring-bucket plan (interpolated), and under an injected
  transient compile fault (degrade walk applies AFTER auto-resolution);
* provenance (``plan_source``) stamping in bench rows and serving
  responses, and resolved-tile/fuse stamping (the row can never
  disagree with the executable).
"""

import json
import os

import jax
import numpy as np
import pytest

from parallel_convolution_tpu import tuning
from parallel_convolution_tpu.ops import oracle
from parallel_convolution_tpu.ops.filters import get_filter
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step as step_lib
from parallel_convolution_tpu.tuning import (
    Plan, PlanCache, Workload, canonical_key, costmodel, search,
)
from parallel_convolution_tpu.tuning.plans import PLAN_SCHEMA


def _mesh(shape=(2, 4)):
    return mesh_lib.make_grid_mesh(
        jax.devices()[: shape[0] * shape[1]], shape)


def _workload(shape=(1, 48, 64), mesh_shape=(2, 4), **kw):
    return Workload.from_mesh(_mesh(mesh_shape), get_filter("blur3"),
                              shape, **kw)


# ------------------------------------------------------------- plan keys
def test_plan_key_stable_across_dict_ordering():
    fields = _workload().key_fields()
    shuffled = dict(reversed(list(fields.items())))
    assert list(fields) != list(shuffled)  # genuinely different order
    assert canonical_key(fields) == canonical_key(shuffled)


def test_plan_key_carries_full_identity():
    base = _workload()
    key = base.key()
    for field, val in [("storage", "bf16"), ("quantize", False),
                       ("boundary", "periodic")]:
        import dataclasses

        other = dataclasses.replace(base, **{field: val})
        assert other.key() != key, f"{field} missing from the key"
    # Same bucket => same key (8000x8000 and 8192x8192 tune identically);
    # different bucket => different key.
    import dataclasses

    assert dataclasses.replace(base, shape=(1, 33, 64)).key() == key
    assert dataclasses.replace(base, shape=(1, 100, 64)).key() != key


# ------------------------------------------------- plan cache persistence
def test_plan_cache_atomic_roundtrip(tmp_path):
    w = _workload()
    cache = PlanCache()
    cache.put(w, Plan("shifted", fuse=4, source="measured",
                      measured_gpx=1.25))
    path = str(tmp_path / "nested" / "plans.json")
    cache.save(path)
    assert os.path.exists(path)
    # No stray tmp files left behind by the atomic write.
    assert [f for f in os.listdir(tmp_path / "nested")] == ["plans.json"]
    loaded = PlanCache.load(path)
    hit = loaded.best_plan(w)
    assert hit is not None and hit.backend == "shifted" and hit.fuse == 4
    assert hit.source == "measured"


def test_plan_cache_corrupt_file_falls_back_empty(tmp_path):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        f.write('{"schema": 1, "plans": {TRUNCATED')
    with pytest.warns(UserWarning, match="unusable plan file"):
        cache = PlanCache.load(path)
    assert len(cache) == 0 and cache.best_plan(_workload()) is None


def test_plan_cache_wrong_schema_ignored(tmp_path):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        json.dump({"schema": PLAN_SCHEMA + 1, "plans": {"k": {}}}, f)
    with pytest.warns(UserWarning, match="schema"):
        cache = PlanCache.load(path)
    assert len(cache) == 0


def test_plan_cache_malformed_record_skipped_not_fatal(tmp_path):
    """A schema-valid file with one bad record must cost a re-tune for
    that key, never crash every backend='auto' resolution."""
    w = _workload()
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        json.dump({"schema": PLAN_SCHEMA,        # record missing 'backend'
                   "plans": {w.key(): {"fuse": 2}}}, f)
    cache = PlanCache.load(path)
    with pytest.warns(UserWarning, match="malformed plan record"):
        assert cache.best_plan(w) is None
    # resolve() falls back to the cost model instead of dying.
    res = tuning.resolve(_mesh(), get_filter("blur3"), (1, 48, 64),
                         plans=cache)
    assert res.source == "predicted"


def test_illegal_pinned_fuse_dies_loudly():
    # 48x64 on 2x4 -> block 24x16: fuse 64 is illegal everywhere; a
    # pinned menu must raise, never silently remeasure fuse=1.
    w = _workload()
    with pytest.raises(ValueError, match="no legal candidates"):
        search.enumerate_candidates(w, fuses=[64])
    with pytest.raises(ValueError, match="no legal candidates"):
        search.enumerate_candidates(w, backends=["pallas"],
                                    tiles=[(1000, 100)])
    # ...and the error surface is the SAME when a plan file is armed:
    # a bucket hit must not smuggle an illegal pin past legality.
    mesh = _mesh()
    filt = get_filter("blur3")
    cache = PlanCache()
    cache.put(Workload.from_mesh(mesh, filt, (1, 48, 64)),
              Plan("shifted", fuse=2, source="measured"))
    with pytest.raises(ValueError, match="no legal candidates"):
        tuning.resolve(mesh, filt, (1, 48, 64), fuse=64, plans=cache)


def test_tile_vmem_legality_is_fuse_aware():
    w = Workload(platform="tpu", device_kind="TPU v5e", grid=(1, 1),
                 shape=(1, 8192, 8192), filter_name="blur3", radius=1,
                 taps_k=3, separable=True, dyadic=True, storage="bf16")
    # A tile near the scoped-VMEM bound at fuse=1 must drop out once the
    # fused window rim pushes it over — per-(tile, fuse) legality.
    per_fuse = {T: search._legal_tiles(w, "pallas", search.TILE_MENU,
                                       fuse=T)
                for T in (1, 32)}
    assert set(per_fuse[32]) <= set(per_fuse[1])
    assert all(search._tile_vmem_ok(w, "pallas", t, 32)
               for t in per_fuse[32] if t is not None)


def test_bench_iterate_threads_boundary():
    from parallel_convolution_tpu.utils import bench

    mesh = _mesh()
    filt = get_filter("blur3")
    rows = {}
    for boundary in ("zero", "periodic"):  # 48/64 divide the 2x4 grid
        rows[boundary] = bench.bench_iterate(
            (48, 64), filt, 2, mesh=mesh, backend="shifted",
            boundary=boundary, reps=1)
    # wall_s, not gpixels_per_s: the tiny workload's throughput rounds
    # to 0.000 under suite load (3-decimal row rounding) — the point
    # here is only that both boundary programs compiled and ran.
    assert all(r["wall_s"] > 0 for r in rows.values())


def test_plan_cache_merge_preserves_other_keys(tmp_path):
    path = str(tmp_path / "plans.json")
    w1, w2 = _workload(), _workload(shape=(1, 300, 300))
    assert w1.key() != w2.key()
    a = PlanCache()
    a.put(w1, Plan("shifted", source="measured"))
    a.save(path)
    b = PlanCache()
    b.put(w2, Plan("xla_conv", source="measured"))
    b.merge_save(path)
    merged = PlanCache.load(path)
    assert len(merged) == 2
    assert merged.exact(w1).backend == "shifted"
    assert merged.exact(w2).backend == "xla_conv"


def test_best_plan_fallback_ladder():
    w = _workload()                      # bucket 64x64
    other = _workload(shape=(1, 200, 200))   # bucket 256x256, same chip
    far = _workload(shape=(1, 2000, 2000))   # bucket 2048x2048
    cache = PlanCache()
    assert cache.best_plan(w) is None    # empty -> None (model fallback)
    cache.put(other, Plan("xla_conv", fuse=2, source="measured"))
    cache.put(far, Plan("shifted", fuse=1, source="measured"))
    hit = cache.best_plan(w)
    # Nearest bucket (256^2 is closer to 64^2 than 2048^2 in log-area),
    # provenance rewritten to 'interpolated'.
    assert hit.backend == "xla_conv" and hit.source == "interpolated"
    cache.put(w, Plan("separable", fuse=8, source="measured"))
    assert cache.best_plan(w).source == "measured"


# ------------------------------------------------------------ cost model
def test_costmodel_fuse_monotone_until_rim_dominates():
    f = lambda T: costmodel.hbm_bytes_per_px_iter(  # noqa: E731
        "pallas", "f32", T, (8, 128), (512, 512), 1)
    series = [f(T) for T in (1, 2, 4, 8, 16, 32)]
    assert all(a > b for a, b in zip(series, series[1:])), series
    # ... until the rim (window overlap) dominates the 1/T saving:
    assert f(64) > f(32)
    # and the recompute tax itself grows strictly with depth.
    assert (costmodel.rim_overhead(1, (8, 128), 1) == 0.0
            < costmodel.rim_overhead(4, (8, 128), 1)
            < costmodel.rim_overhead(16, (8, 128), 1))


def test_costmodel_storage_and_interpret_penalty():
    t = lambda backend, storage: costmodel.predict_seconds_per_px_iter(  # noqa: E731
        backend, storage, 8, None, (1, 8192, 8192), (8192, 8192), (1, 1),
        3, True, True, costmodel.TPU_V5E)
    # Narrower carries never predict slower on the bandwidth side.
    assert t("pallas", "bf16") <= t("pallas", "f32")
    # Interpreted Pallas must lose to compiled XLA off-TPU.
    cpu = costmodel.hardware_for("cpu")
    tc = lambda backend: costmodel.predict_seconds_per_px_iter(  # noqa: E731
        backend, "f32", 1, None, (1, 256, 256), (128, 64), (2, 4),
        3, True, True, cpu)
    assert tc("pallas") > tc("shifted") * 100


def test_costmodel_constants_match_kernel_modules():
    """The model mirrors kernel constants it cannot import (jax-free);
    this pin makes drift a test failure instead of a silent mistune."""
    from parallel_convolution_tpu.ops import pallas_rdma, pallas_stencil

    assert costmodel.DEFAULT_TILE == pallas_stencil.DEFAULT_TILE
    assert costmodel.SEP_TILE == pallas_stencil.SEP_TILE
    assert costmodel.RDMA_TILED_VMEM_BYTES == pallas_rdma._TILED_VMEM_BYTES
    import jax.numpy as jnp

    for name, dt in [("f32", jnp.float32), ("bf16", jnp.bfloat16),
                     ("u8", jnp.uint8)]:
        assert costmodel.SUBLANE[name] == pallas_stencil._sublane(dt)


# ------------------------------------------------------ candidate space
def test_candidate_legality():
    w = _workload(shape=(1, 48, 64))  # block 24x16, radius 1
    cands = search.enumerate_candidates(w)
    assert cands, "empty candidate space"
    for c in cands:
        assert c.fuse * w.radius <= min(w.block_hw)
        assert c.tile is None  # every menu tile exceeds this tiny block
    # Separable tiers are in (blur3 is dyadic + quantize mode)...
    assert {c.backend for c in cands} >= {"shifted", "separable"}
    # ...but OUT for non-dyadic or float-mode workloads (byte safety).
    w_float = _workload(shape=(1, 48, 64), quantize=False)
    assert not any(c.backend in ("separable", "pallas_sep")
                   for c in search.enumerate_candidates(w_float))


def test_candidate_tiles_alignment_and_vmem():
    w = Workload(platform="tpu", device_kind="TPU v5e", grid=(1, 1),
                 shape=(1, 8192, 8192), filter_name="blur3", radius=1,
                 taps_k=3, separable=True, dyadic=True, storage="bf16")
    tiles = search._legal_tiles(w, "pallas", search.TILE_MENU)
    sub = costmodel.SUBLANE["bf16"]
    for t in tiles:
        if t is not None:
            assert t[0] % sub == 0 and t[1] % costmodel.LANE == 0
    # The 2D tap loop's scoped-VMEM bound excludes the tiles that failed
    # Mosaic compile on silicon (1024x512 f32: 25.3 MB vs 16 MB).
    assert (1024, 512) not in tiles
    assert (1024, 512) in search._legal_tiles(w, "pallas_sep",
                                              search.TILE_MENU)


def test_dry_run_tune_is_deterministic_and_device_free():
    w = _workload()
    r1 = search.tune(w, dry_run=True)
    r2 = search.tune(w, dry_run=True)
    assert r1.plan == r2.plan
    assert r1.plan.source == "predicted" and r1.rows == []


# ------------------------------------------------- backend="auto" (e2e)
def test_auto_resolves_deterministically():
    mesh = _mesh()
    filt = get_filter("blur3")
    r1 = tuning.resolve(mesh, filt, (1, 48, 64), plans=PlanCache())
    r2 = tuning.resolve(mesh, filt, (1, 48, 64), plans=PlanCache())
    assert r1 == r2
    assert r1.source == "predicted"
    assert r1.backend in ("shifted", "xla_conv", "separable")  # compiled
    #   XLA tier on CPU: interpreted Pallas must never win off-TPU


def test_auto_bitexact_vs_explicit_and_oracle():
    mesh = _mesh()
    filt = get_filter("blur3")
    rng = np.random.default_rng(7)
    img = rng.integers(0, 256, size=(37, 53)).astype(np.uint8)
    x = img[None].astype(np.float32)
    out_auto = np.asarray(step_lib.sharded_iterate(
        x, filt, 5, mesh, backend="auto", fuse=None)).astype(np.uint8)
    res = tuning.last_resolution()
    out_exp = np.asarray(step_lib.sharded_iterate(
        x, filt, 5, mesh, backend=res.backend, fuse=res.fuse,
        tile=res.tile)).astype(np.uint8)
    assert np.array_equal(out_auto, out_exp)
    assert np.array_equal(out_auto[0], oracle.run_serial_u8(img, filt, 5))


def test_auto_pins_override_tuned_knobs():
    mesh = _mesh()
    res = tuning.resolve(mesh, get_filter("blur3"), (1, 48, 64),
                         fuse=2, plans=PlanCache())
    assert res.fuse == 2  # the pin is honored verbatim


def test_auto_uses_plan_file_via_env(tmp_path, monkeypatch):
    mesh = _mesh()
    filt = get_filter("blur3")
    w = Workload.from_mesh(mesh, filt, (1, 48, 64))
    cache = PlanCache()
    cache.put(w, Plan("xla_conv", fuse=2, source="measured",
                      measured_gpx=0.5))
    path = str(tmp_path / "plans.json")
    cache.save(path)
    monkeypatch.setenv(tuning.PLAN_FILE_ENV, path)
    res = tuning.resolve(mesh, filt, (1, 48, 64))
    assert (res.backend, res.fuse, res.source) == ("xla_conv", 2,
                                                   "measured")
    # The measured-plan path serves the same bytes as the oracle.
    rng = np.random.default_rng(11)
    img = rng.integers(0, 256, size=(48, 64)).astype(np.uint8)
    out = np.asarray(step_lib.sharded_iterate(
        img[None].astype(np.float32), filt, 3, mesh, backend="auto",
        fuse=None)).astype(np.uint8)
    assert np.array_equal(out[0], oracle.run_serial_u8(img, filt, 3))
    monkeypatch.delenv(tuning.PLAN_FILE_ENV)
    res2 = tuning.resolve(mesh, filt, (1, 48, 64))
    assert res2.source == "predicted"


def test_interpolated_plan_clamps_illegal_fuse():
    mesh = _mesh()
    filt = get_filter("blur3")
    big = Workload.from_mesh(mesh, filt, (1, 2048, 2048))
    cache = PlanCache()
    cache.put(big, Plan("shifted", fuse=32, source="measured"))
    # 48x64 on 2x4 -> block 24x16: fuse 32 is illegal (r*T > block) and
    # must be clamped, not handed to the kernels to die on.
    res = tuning.resolve(mesh, filt, (1, 48, 64), plans=cache)
    assert res.source == "interpolated"
    assert res.fuse * filt.radius <= 16
    # And the interpolated plan's bytes still match the oracle.
    rng = np.random.default_rng(13)
    img = rng.integers(0, 256, size=(48, 64)).astype(np.uint8)
    out = np.asarray(step_lib.sharded_iterate(
        img[None].astype(np.float32), filt, 3, mesh,
        backend=res.backend, fuse=res.fuse, tile=res.tile)
    ).astype(np.uint8)
    assert np.array_equal(out[0], oracle.run_serial_u8(img, filt, 3))


# ------------------------------------------------ provenance in bench rows
def test_bench_row_stamps_plan_source_and_resolved_knobs():
    from parallel_convolution_tpu.utils import bench

    mesh = _mesh()
    filt = get_filter("blur3")
    row = bench.bench_iterate((48, 64), filt, 3, mesh=mesh,
                              backend="auto", fuse=None, reps=1)
    assert row["backend"] == "auto"
    assert row["plan_source"] == "predicted"
    assert row["effective_backend"] in ("shifted", "xla_conv", "separable")
    # Resolved-then-clamped fuse actually compiled (iters=3 bounds it),
    # never the caller-passed None.
    assert isinstance(row["fuse"], int) and 1 <= row["fuse"] <= 3
    assert row["predicted_gpx_per_chip"] > 0


def test_bench_row_stamps_fuse_clamp_and_default_tile():
    from parallel_convolution_tpu.utils import bench

    mesh = _mesh((1, 1))
    filt = get_filter("blur3")
    row = bench.bench_iterate((16, 128), filt, 2, mesh=mesh,
                              backend="shifted", fuse=8, reps=1)
    # The executable was compiled with fuse clamped to iters=2: the row
    # must record 2, not the caller's 8 (rows can't disagree with code).
    assert row["fuse"] == 2 and row["tile"] is None
    assert row["plan_source"] == "explicit"
    row = bench.bench_iterate((16, 128), filt, 1, mesh=mesh,
                              backend="pallas", reps=1)
    # Pallas launches always have a tile; None meant the module default.
    assert row["tile"] == "%dx%d" % costmodel.DEFAULT_TILE


def test_auto_with_plan_survives_transient_compile_fault():
    """The acceptance trio's third leg: auto resolves (from a measured
    plan) to a Pallas tier, an injected transient compile fault fires,
    and the degrade walk still applies AFTER auto-resolution — output
    stays byte-identical to the oracle and the row records everything.
    """
    from parallel_convolution_tpu.resilience import degrade, faults
    from parallel_convolution_tpu.utils import bench

    mesh = _mesh()
    filt = get_filter("blur3")
    # Unique shape => cold _build_iterate lru_cache => the probe compile
    # really consults the backend_compile fault site.
    shape = (1, 44, 60)
    w = Workload.from_mesh(mesh, filt, shape)
    cache = PlanCache()
    cache.put(w, Plan("pallas", fuse=1, source="measured",
                      measured_gpx=9.9))
    degrade.clear_probe_cache()
    try:
        with faults.injected("backend_compile:1"):
            with pytest.warns(degrade.BackendDegradedWarning):
                res = tuning.resolve(mesh, filt, shape, plans=cache)
                assert (res.backend, res.source) == ("pallas", "measured")
                eff = degrade.resolve_backend(
                    mesh, filt, res.backend, fuse=res.fuse, tile=res.tile,
                    block_hw=(22, 15))
            assert eff == "shifted"  # walked pallas -> shifted
    finally:
        degrade.clear_probe_cache()

    # End to end through sharded_iterate(fallback=True): same fault
    # plan, bytes must match the oracle on the degraded tier.
    rng = np.random.default_rng(3)
    img = rng.integers(0, 256, size=(44, 60)).astype(np.uint8)
    x = img[None].astype(np.float32)
    monkey_cache_path = None
    try:
        import tempfile

        monkey_cache_path = os.path.join(tempfile.mkdtemp(), "p.json")
        cache.save(monkey_cache_path)
        os.environ[tuning.PLAN_FILE_ENV] = monkey_cache_path
        degrade.clear_probe_cache()
        with faults.injected("backend_compile:1"):
            with pytest.warns(degrade.BackendDegradedWarning):
                out = np.asarray(step_lib.sharded_iterate(
                    x, filt, 4, mesh, backend="auto", fuse=None,
                    fallback=True)).astype(np.uint8)
        assert np.array_equal(out[0], oracle.run_serial_u8(img, filt, 4))
    finally:
        os.environ.pop(tuning.PLAN_FILE_ENV, None)
        degrade.clear_probe_cache()

    # Provenance still stamped on the bench row for the same setup.
    degrade.clear_probe_cache()
    row = bench.bench_iterate(shape[1:], filt, 2, mesh=mesh,
                              backend="auto", fuse=None, reps=1)
    assert row["plan_source"] == "predicted"  # env cleared: model path


# ------------------------------------------------------- serving surface
def test_engine_auto_key_shares_executable_and_stamps_source(tmp_path):
    from parallel_convolution_tpu.serving.engine import WarmEngine

    mesh = _mesh()
    filt = get_filter("blur3")
    w = Workload.from_mesh(mesh, filt, (1, 48, 64), storage="f32")
    cache = PlanCache()
    cache.put(w, Plan("shifted", fuse=2, source="measured",
                      measured_gpx=1.0))
    path = str(tmp_path / "plans.json")
    cache.save(path)

    eng = WarmEngine(mesh, plans=path)
    k_auto = eng.key_for((1, 48, 64), backend="auto", fuse=None, iters=4)
    k_expl = eng.key_for((1, 48, 64), backend="shifted", fuse=2, iters=4)
    # Auto and explicit requests for the tuned config share ONE key
    # (hence one warm executable).
    assert k_auto == k_expl
    entry = eng.entry(k_auto)
    assert entry.plan_source == "measured"
    assert eng.stats["compiles"] == 1
    eng.entry(k_expl)
    assert eng.stats["compiles"] == 1  # no recompilation

    x = np.random.default_rng(0).integers(
        0, 256, (2, 1, 48, 64)).astype(np.float32)
    out, info = eng.run_batch(k_auto, x)
    assert info["plan_source"] == "measured"
    assert info["predicted_gpx_per_chip"] is not None
    snap = eng.snapshot()
    assert snap["resident"][0]["plan_source"] == "measured"


def test_service_warmup_with_plan_file_boots_tuned(tmp_path):
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request,
    )

    mesh = _mesh()
    filt = get_filter("blur3")
    w = Workload.from_mesh(mesh, filt, (1, 48, 64))
    cache = PlanCache()
    cache.put(w, Plan("xla_conv", fuse=1, source="measured",
                      measured_gpx=1.0))
    path = str(tmp_path / "plans.json")
    cache.save(path)

    svc = ConvolutionService(mesh, max_delay_s=0.001)
    try:
        effs = svc.warmup([{"rows": 48, "cols": 64, "iters": 2,
                            "backend": "auto", "fuse": None}],
                          plan_file=path)
        assert effs == ["xla_conv"]
        img = np.random.default_rng(1).integers(
            0, 256, (48, 64)).astype(np.uint8)
        resp = svc.submit(Request(image=img, iters=2, backend="auto",
                                  fuse=None))
        assert resp.ok and resp.effective_backend == "xla_conv"
        assert resp.plan_source == "measured"
        assert resp.predicted_gpx_per_chip is not None
        # Warmed key + auto request shared the executable: zero extra
        # compiles beyond the warmup one.
        assert svc.engine.stats["compiles"] == 1
        # Explicit requests still stamp 'explicit' — even when they hit
        # the SAME warm entry an auto request built (provenance is
        # per-request, not per-entry).
        resp2 = svc.submit(Request(image=img, iters=2, backend="xla_conv",
                                   fuse=1))
        assert resp2.ok and resp2.plan_source == "explicit"
        # fuse=None with an explicit backend is the same contract error
        # every other entry point rejects: typed invalid, not fuse=1.
        rej = svc.submit(Request(image=img, iters=2, backend="shifted",
                                 fuse=None))
        assert not rej.ok and rej.reason == "invalid"
    finally:
        svc.close()


def test_runconfig_accepts_auto():
    from parallel_convolution_tpu.utils.config import RunConfig

    cfg = RunConfig(rows=48, cols=64, backend="auto", fuse=None)
    rt = RunConfig.from_json(cfg.to_json())
    assert rt.backend == "auto" and rt.fuse is None
    with pytest.raises(ValueError, match="auto"):
        RunConfig(rows=48, cols=64, backend="shifted", fuse=None)


# --------------------------------------------- round 10: elastic tuning
def test_plan_key_check_every_identity():
    """check_every joins the key ONLY when set: fixed-count keys are
    byte-identical to the pre-round-10 schema (existing plan files stay
    valid), convergence keys are distinct per cadence."""
    base = _workload()
    assert "check_every" not in base.key_fields()
    ce5 = _workload(check_every=5)
    ce9 = _workload(check_every=9)
    assert ce5.key() != base.key() and ce5.key() != ce9.key()
    assert ce5.key_fields()["check_every"] == 5


def test_check_every_bounds_legal_fuse():
    """A convergence chunk fuses at most its n-1 pre-pair iterations, so
    the candidate space (and a pinned fuse) must respect check_every."""
    w = _workload(check_every=3)
    assert max(search._legal_fuses(w, "shifted", search.FUSE_MENU)) == 2
    assert search._legal_fuses(_workload(check_every=1), "shifted",
                               search.FUSE_MENU) == [1]
    res = tuning.resolve(_mesh(), get_filter("blur3"), (1, 48, 64),
                         fuse=8, check_every=3)
    assert res.fuse == 2  # pinned depth clamped as _build_converge would


def test_cross_grid_plan_interpolates(tmp_path):
    """Elastic recovery: a resharded resume on a new grid resolves the
    run's tuned plan (provenance 'interpolated'), not the cost model —
    and a same-grid neighbor still beats a cross-grid one."""
    w_old = _workload(mesh_shape=(2, 4))           # the grid that tuned
    w_new = _workload(mesh_shape=(1, 2))           # the survivor grid
    cache = PlanCache()
    cache.put(w_old, Plan("xla_conv", fuse=4, source="measured"))
    hit = cache.best_plan(w_new)
    assert hit is not None and hit.backend == "xla_conv"
    assert hit.source == "interpolated"
    res = tuning.resolve(_mesh((1, 2)), get_filter("blur3"), (1, 48, 64),
                         plans=cache)
    assert res.backend == "xla_conv" and res.source == "interpolated"
    # A same-grid different-bucket plan outranks any cross-grid one.
    w_new_big = _workload(shape=(1, 200, 200), mesh_shape=(1, 2))
    cache.put(w_new_big, Plan("separable", fuse=2, source="measured"))
    assert cache.best_plan(w_new).backend == "separable"
    # Field-set parity: a convergence-tuned plan never drives the
    # fixed-count path (and vice versa).
    conv_only = PlanCache()
    conv_only.put(_workload(check_every=5),
                  Plan("pallas", fuse=2, source="measured"))
    assert conv_only.best_plan(_workload()) is None
    assert conv_only.best_plan(_workload(check_every=5)) is not None


def test_converge_auto_resolves_from_cross_grid_plan(tmp_path, monkeypatch):
    """End to end: sharded_converge(backend='auto', check_every=...) on a
    SHRUNKEN mesh resolves through a plan file tuned on the big mesh —
    the resharded-resume scenario — and stays byte-identical to the
    explicit backend."""
    from parallel_convolution_tpu.utils import imageio

    filt = get_filter("jacobi3")
    cache = PlanCache()
    cache.put(Workload.from_mesh(_mesh((2, 4)), filt, (1, 40, 48),
                                 quantize=False, check_every=4),
              Plan("xla_conv", fuse=2, source="measured"))
    plan_file = tmp_path / "plans.json"
    cache.save(str(plan_file))
    monkeypatch.setenv(tuning.PLAN_FILE_ENV, str(plan_file))
    img = imageio.generate_test_image(40, 48, "grey", seed=7)
    x = img[None].astype(np.float32)
    got, it_auto = step_lib.sharded_converge(
        x, filt, tol=0.05, max_iters=24, check_every=4, mesh=_mesh((1, 2)),
        quantize=False, backend="auto", fuse=None)
    assert tuning.last_resolution().source == "interpolated"
    assert tuning.last_resolution().backend == "xla_conv"
    want, it_ref = step_lib.sharded_converge(
        x, filt, tol=0.05, max_iters=24, check_every=4, mesh=_mesh((1, 2)),
        quantize=False, backend="xla_conv", fuse=2)
    assert it_auto == it_ref
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
