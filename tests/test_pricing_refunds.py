"""Pricing refund semantics on cache hits, dead-end sheds, and hedges.

ISSUE 18 satellite: the admission charge is settled so a tenant pays
for device work EXACTLY once per device execution —

* a response stamped ``cache: "hit"`` consumed no device time, so the
  router refunds the admission charge down to ``WorkPricer.hit_units``
  (the floor): a duplicate-heavy tenant's budget outlasts its naive
  ``n_requests * cost`` ceiling;
* a shed that did no work (``replica_unavailable`` et al.) refunds the
  FULL charge — a dead replica set burns availability, never quota;
* a hedged request admits ONE charge no matter how many dispatch
  attempts race — hedging spends the operator's device time, not the
  tenant's budget twice.

All constructions use rate ≈ 0 buckets so the balance arithmetic is
exact: whatever passes, passes on refunds alone, not on refill.
"""

from __future__ import annotations

import base64
import threading

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.serving.cache import ResultCache
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.serving.router import (
    InProcessReplica, ReplicaRouter, TenantQuotas,
)
from parallel_convolution_tpu.serving.service import ConvolutionService
from parallel_convolution_tpu.utils import imageio

_NO_REFILL = 1e-9   # rate: bucket never meaningfully refills in-test


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "blur3", "iters": 1}
    body.update(kw)
    return body


def _router(burst_units, *, pricer, n=1, cache=None, **kw):
    def make():
        return ConvolutionService(_mesh(), max_delay_s=0.002,
                                  cache=cache)

    reps = [InProcessReplica(make, name=f"r{i}") for i in range(n)]
    return ReplicaRouter(
        reps, quotas=TenantQuotas(rate=_NO_REFILL, burst=burst_units),
        pricer=pricer, poll_interval_s=0.05, **kw)


def _pricer():
    # min_units must sit far below one real job's price: the refund
    # under test is `cost - hit_units`, which the default 1e-4 floor
    # could swallow for a tiny CPU job.
    return WorkPricer(grid=(1, 2), min_units=1e-9)


def test_hit_units_is_the_floor_and_prices_cache_hits():
    p = _pricer()
    body = _body(_img())
    assert p.hit_units() == pytest.approx(1e-9)
    assert p.price(body, cache_hit=True) == p.hit_units()
    assert p.price(body) > 100 * p.hit_units()


def test_cache_hits_refund_down_to_hit_units():
    pricer = _pricer()
    img = _img(seed=21)
    cost = pricer.price(_body(img))
    # Budget = 3 device executions.  10 duplicates cost ONE execution
    # plus 9 hit floors under refund settlement; without the hit
    # refund the 4th duplicate sheds tenant_quota.
    router = _router(3 * cost, pricer=pricer, cache=ResultCache())
    try:
        for i in range(10):
            status, wire = router.request(
                _body(img, request_id=f"hit{i}"), timeout=120)
            assert status == 200 and wire["ok"], (i, wire)
            assert wire["cache"] == ("miss" if i == 0 else "hit"), i
            assert wire["router"]["cost_units"] == round(cost, 6)
        assert router.stats["rejected_tenant_quota"] == 0
        bucket = router.quotas.bucket("default")
        # One real execution + 9 floors: balance ≈ 2·cost remains.
        assert bucket._tokens == pytest.approx(2 * cost, rel=1e-3)
        # The refund is bounded: a MISS (new content) still pays full.
        status, wire = router.request(
            _body(_img(seed=99), request_id="fresh"), timeout=120)
        assert status == 200 and wire["cache"] == "miss"
        assert bucket._tokens == pytest.approx(cost, rel=1e-3)
    finally:
        router.close()


def test_no_work_sheds_refund_full_charge():
    pricer = _pricer()
    img = _img(seed=22)
    cost = pricer.price(_body(img))
    # Budget = exactly ONE charge.  Against a dead replica set every
    # attempt must come back replica_unavailable: if the dead-end shed
    # kept the charge, attempt 2 would flip to tenant_quota — turning
    # an operator outage into a tenant bill.
    router = _router(cost, pricer=pricer)
    try:
        router.replica("r0").kill()
        for i in range(5):
            status, wire = router.request(
                _body(img, request_id=f"dead{i}"), timeout=30)
            assert status == 503, (i, wire)
            assert wire["rejected"] == "replica_unavailable", i
        assert router.stats["rejected_tenant_quota"] == 0
        assert router.quotas.bucket("default")._tokens == pytest.approx(
            cost, rel=1e-3)
    finally:
        router.close()


def test_hedged_request_admits_exactly_one_charge():
    pricer = _pricer()
    img = _img(seed=23)
    cost = pricer.price(_body(img))
    # hedge_s=0 → the hedge fires on every request.  Budget 1.5·cost
    # admits ONE charge with headroom but NOT two: a per-attempt charge
    # would shed this request at its own second dispatch.
    router = _router(1.5 * cost, pricer=pricer, n=2, hedge_s=0.0)
    try:
        status, wire = router.request(
            _body(img, request_id="hedge0"), timeout=120)
        assert status == 200 and wire["ok"], wire
        assert router.stats["hedges"] >= 1
        assert router.stats["rejected_tenant_quota"] == 0
        assert router.quotas.bucket("default")._tokens == pytest.approx(
            0.5 * cost, rel=1e-3)
    finally:
        router.close()


def test_dedup_joiners_share_the_single_charge():
    # Two submissions, ONE request_id, one replica: the replica-side
    # idempotency ledger dedups them into one device execution, and the
    # bucket shows exactly two admission charges were taken at the
    # router (dedup is replica-side; each router-level request is a
    # distinct admission) minus nothing — i.e. joiners are NOT free at
    # admission, but no third hidden charge appears either.
    pricer = _pricer()
    img = _img(seed=24)
    cost = pricer.price(_body(img))
    router = _router(4 * cost, pricer=pricer)
    try:
        results = []

        def go():
            results.append(router.request(
                _body(img, request_id="dup-join"), timeout=120))

        ts = [threading.Thread(target=go) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(s == 200 and w["ok"] for s, w in results)
        svc = router.replica("r0").service
        assert svc.engine.stats["images"] == 1   # one device execution
        charged = 4 * cost - router.quotas.bucket("default")._tokens
        assert charged == pytest.approx(2 * cost, rel=1e-3)
    finally:
        router.close()
