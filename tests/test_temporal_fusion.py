"""Temporal fusion (fuse=T): T iterations per halo exchange, bit-exact."""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio
from parallel_convolution_tpu.utils.jax_compat import IS_MODERN_JAX


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def _run(img, filt, iters, mshape, **kw):
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    out = step.sharded_iterate(x, filt, iters, mesh=_mesh(mshape),
                               quantize=True, **kw)
    return imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))


@pytest.mark.parametrize("fuse", [2, 3, 5])
def test_fused_bitexact_vs_oracle(grey_odd, fuse):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 10)
    got = _run(grey_odd, filt, 10, (2, 4), fuse=fuse)
    np.testing.assert_array_equal(got, want)


def test_fused_remainder_path(grey_odd):
    # 7 iters, fuse 3 -> chunks 3+3 then tail of 1
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 7)
    got = _run(grey_odd, filt, 7, (2, 2), fuse=3)
    np.testing.assert_array_equal(got, want)


def test_fused_radius2_rgb(rgb_odd):
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 4)
    got = _run(rgb_odd, filt, 4, (2, 2), fuse=2)
    np.testing.assert_array_equal(got, want)


def test_fused_bf16(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 8)
    got = _run(grey_odd, filt, 8, (2, 4), fuse=4, storage="bf16")
    np.testing.assert_array_equal(got, want)


def test_fused_pallas_backend(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    got = _run(grey_odd, filt, 6, (2, 2), fuse=3, backend="pallas")
    np.testing.assert_array_equal(got, want)


def test_fuse_too_deep_raises(grey_small):
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    with pytest.raises(ValueError, match="fuse"):
        # 24-row image on 8-row grid -> 3-row blocks; fuse=20 needs 20-deep
        step.sharded_iterate(x, filt, 40, mesh=_mesh((8, 1)), fuse=20)


def _slab_depths(fn, xs):
    """Halo-slab depths of every collective-permute in ``fn``'s HLO."""
    import re

    hlo = fn.lower(xs).compile().as_text()
    shapes = re.findall(
        r"f32\[1,(\d+),(\d+)\][^\n]*collective-permute", hlo
    )
    assert shapes, "no collective-permute in HLO"
    return {min(int(a), int(b)) for a, b in shapes}


@pytest.mark.skipif(not IS_MODERN_JAX, reason="HLO slab-shape pin targets the current shard_map lowering (old lowerings emit extra collective-permutes)")
def test_fused_halo_exchanges_deep_slabs(grey_small):
    # fuse=5 must exchange 5-deep halo slabs once per chunk (1/5 the
    # collective rounds of fuse=1, whose slabs are 1-deep).
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    xs, valid_hw, block_hw = step._prepare(x, m, 1)

    def depths(fuse):
        return _slab_depths(step._build_iterate(
            m, filt, 10, True, valid_hw, block_hw, "shifted", fuse), xs)

    assert depths(1) == {1}
    assert depths(5) == {5}


@pytest.mark.skipif(not IS_MODERN_JAX, reason="HLO slab-shape pin targets the current shard_map lowering (old lowerings emit extra collective-permutes)")
def test_fused_convergence_exchanges_deep_slabs(grey_small):
    """The round-4 fused convergence path must carry the same structural
    saving: inside the while_loop chunk, fused steps exchange fuse-deep
    slabs (one collective round per fuse iterations) — asserted in the
    compiled HLO, no silicon needed."""
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    xs, valid_hw, block_hw = step._prepare(x, m, 1)

    def depths(fuse):
        return _slab_depths(step._build_converge(
            m, filt, 0.5, 40, 10, True, valid_hw, block_hw, "shifted",
            "zero", fuse), xs)

    assert depths(1) == {1}
    # Fused program contains BOTH depths: 4-deep slabs in the fused
    # fori_loop plus 1-deep in the remainder/pair-forming single steps.
    assert depths(4) == {1, 4}


@pytest.mark.parametrize("fuse", [2, 4])
@pytest.mark.parametrize("storage", ["f32", "bf16"])
def test_fused_pallas_kernel_bitexact(grey_odd, fuse, storage):
    # The in-VMEM multi-level kernel path (backend=pallas, fuse>1).
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 8)
    got = _run(grey_odd, filt, 8, (2, 4), fuse=fuse, backend="pallas",
               storage=storage)
    np.testing.assert_array_equal(got, want)


def test_fused_pallas_kernel_rgb_gaussian5(rgb_odd):
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 4)
    got = _run(rgb_odd, filt, 4, (2, 2), fuse=2, backend="pallas")
    np.testing.assert_array_equal(got, want)


def test_fused_pallas_kernel_float_mode():
    filt = filters.get_filter("jacobi3")
    img = imageio.generate_test_image(32, 40, "grey", seed=41)
    want = oracle.run_serial_f32(img.astype(np.float32), filt, 6)
    x = img[None].astype(np.float32)
    out = step.sharded_iterate(x, filt, 6, mesh=_mesh((2, 2)),
                               quantize=False, backend="pallas", fuse=3)
    np.testing.assert_array_equal(np.asarray(out)[0], want)


def test_fused_pallas_multi_tile():
    # Block large enough to need a multi-tile pallas grid inside shard_map.
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(48, 300, "grey", seed=42)
    want = oracle.run_serial_u8(img, filt, 4)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    from parallel_convolution_tpu.ops import pallas_stencil
    old = pallas_stencil.DEFAULT_TILE
    pallas_stencil.DEFAULT_TILE = (16, 128)
    try:
        out = step._build_iterate.__wrapped__(
            _mesh((2, 2)), filt, 4, True, (48, 300), (24, 150), "pallas", 2
        )(step._prepare(x, _mesh((2, 2)), 1)[0])
    finally:
        pallas_stencil.DEFAULT_TILE = old
    got = np.asarray(out)[:, :48, :300].astype(np.uint8)
    np.testing.assert_array_equal(got[0], want)


def test_interior_range_geometry():
    # The split's static tile classification, directly.
    from parallel_convolution_tpu.ops.pallas_stencil import _interior_range

    # 45x300, tile 8x128, depth 3: rows [1,4] of 6, col [1,1] of 3.
    assert _interior_range((45, 300), (8, 128), 3, (6, 3)) == ((1, 4), (1, 1))
    # Too narrow for any interior column -> no split.
    assert _interior_range((45, 150), (8, 128), 3, (6, 2)) is None
    # Depth deeper than one tile row: i_lo rounds up past row 1.
    assert _interior_range((64, 300), (8, 128), 10, (8, 3)) == ((2, 5), (1, 1))


@pytest.mark.parametrize("hw,tile", [
    ((45, 300), (8, 128)),    # interior = rows [1,4] x col [1,1]
    ((45, 150), (8, 128)),    # no interior column -> fallback single call
    ((64, 520), (16, 128)),   # dividing height, 2 interior cols
])
def test_interior_split_bitexact(hw, tile):
    # Unmasked-interior launch split vs the single masked call: identical
    # bytes whether the geometry yields several, one, or zero interior
    # tiles (the zero case must silently fall back).
    img = imageio.generate_test_image(hw[0], hw[1], "grey", seed=19)
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    m = _mesh((1, 1))
    base = step.sharded_iterate(x, filt, 6, mesh=m, quantize=True,
                                backend="pallas_sep", fuse=3, tile=tile)
    split = step.sharded_iterate(x, filt, 6, mesh=m, quantize=True,
                                 backend="pallas_sep", fuse=3, tile=tile,
                                 interior_split=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(split))
    want = oracle.run_serial_u8(img, filt, 6)
    got = imageio.planar_to_interleaved(np.asarray(split).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_interior_split_rgb_radius2_u8():
    # radius-2 filter (deeper rings), RGB, u8 carries, non-dividing shape
    # wide enough that the split is genuinely active.
    from parallel_convolution_tpu.ops.pallas_stencil import _interior_range

    img = imageio.generate_test_image(45, 300, "rgb", seed=21)
    filt = filters.get_filter("gaussian5")
    assert _interior_range((45, 300), (8, 128), 2 * 2, (6, 3)) is not None
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    m = _mesh((1, 1))
    out = step.sharded_iterate(x, filt, 4, mesh=m, quantize=True,
                               backend="pallas", storage="u8", fuse=2,
                               tile=(8, 128), interior_split=True)
    want = oracle.run_serial_u8(img, filt, 4)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_interior_split_noop_on_fuse1(grey_odd):
    # The split only exists on the fused (fuse > 1) Pallas kernel path;
    # with fuse=1 the flag must be a silent no-op with identical results.
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    m = _mesh((1, 1))
    a = step.sharded_iterate(x, filt, 3, mesh=m, quantize=True,
                             backend="pallas_sep", fuse=1)
    b = step.sharded_iterate(x, filt, 3, mesh=m, quantize=True,
                             backend="pallas_sep", fuse=1,
                             interior_split=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_interior_range_offset_classes():
    # The per-axis offset classes and the offset-range interior ranges
    # that make the split sound on multi-device grids.
    from parallel_convolution_tpu.ops.pallas_stencil import (
        _interior_range, axis_offset_classes)

    assert axis_offset_classes(1, 64) == [(0, 0)]
    assert axis_offset_classes(2, 64) == [(0, 0), (64, 64)]
    assert axis_offset_classes(4, 64) == [(0, 0), (64, 128), (192, 192)]
    # Image (128, 512) on a 2x2 device grid -> blocks (64, 256), kernel
    # tiles (16, 128) -> per-block tile grid (4, 2), depth 4.
    # Top-left block (offset (0, 0)): tile row 0 / col 0 cross the image's
    # top/left edge; the bottom/right tiles see neighbor data via the halo,
    # so they are interior w.r.t. the IMAGE.
    assert _interior_range((128, 512), (16, 128), 4, (4, 2),
                           ((0, 0), (0, 0))) == ((1, 3), (1, 1))
    # Bottom-right block (offset (64, 256)): the far tiles cross H/W.
    assert _interior_range((128, 512), (16, 128), 4, (4, 2),
                           ((64, 64), (256, 256))) == ((0, 2), (0, 0))
    # Middle-band row range (offsets 64..128 of a 4-high grid over 256
    # rows): conservative over the whole band -> every tile row interior.
    assert _interior_range((256, 512), (16, 128), 4, (4, 4),
                           ((64, 128), (0, 0))) == ((0, 3), (1, 2))


@pytest.mark.parametrize("mshape", [(2, 2), (2, 4), (4, 2)])
def test_interior_split_multichip_bitexact(mshape):
    # The generalized split on real multi-device grids: every device
    # dispatches to its edge-class launch, masked borders keep dynamic
    # offsets, and the bytes match both the unsplit run and the oracle.
    # 90x300 is non-divisible by every grid here (pad-rim devices too).
    img = imageio.generate_test_image(90, 300, "grey", seed=23)
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    m = _mesh(mshape)
    kw = dict(quantize=True, backend="pallas_sep", fuse=3, tile=(8, 128))
    base = step.sharded_iterate(x, filt, 6, mesh=m, **kw)
    split = step.sharded_iterate(x, filt, 6, mesh=m, interior_split=True,
                                 **kw)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(split))
    want = oracle.run_serial_u8(img, filt, 6)
    got = imageio.planar_to_interleaved(np.asarray(split).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_interior_split_multichip_u8():
    # u8 carries (sublane 32 -> coarser tile rounding) + the class split
    # on a 2x2 grid; bit-exact vs unsplit and the oracle.
    img = imageio.generate_test_image(90, 300, "grey", seed=31)
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    m = _mesh((2, 2))
    kw = dict(quantize=True, backend="pallas_sep", fuse=3, tile=(8, 128),
              storage="u8")
    base = step.sharded_iterate(x, filt, 6, mesh=m, **kw)
    split = step.sharded_iterate(x, filt, 6, mesh=m, interior_split=True,
                                 **kw)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(split))
    want = oracle.run_serial_u8(img, filt, 6)
    got = imageio.planar_to_interleaved(np.asarray(split).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_interior_split_multichip_bf16_radius2():
    # Deep rings (radius-2, fuse=2 -> depth 4) + bf16 carries on a 2x2
    # grid; bit-exact vs the unsplit fused path and the oracle.
    img = imageio.generate_test_image(64, 300, "rgb", seed=29)
    filt = filters.get_filter("gaussian5")
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    m = _mesh((2, 2))
    kw = dict(quantize=True, backend="pallas", fuse=2, tile=(8, 128),
              storage="bf16")
    base = step.sharded_iterate(x, filt, 4, mesh=m, **kw)
    split = step.sharded_iterate(x, filt, 4, mesh=m, interior_split=True,
                                 **kw)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(split))
    want = oracle.run_serial_u8(img, filt, 4)
    got = imageio.planar_to_interleaved(np.asarray(split).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_interior_split_requires_block_off():
    # ADVICE r4: the unmasked-interior contract is now enforced — a direct
    # caller on a sharded layout cannot silently skip ghost-ring masking.
    from parallel_convolution_tpu.ops import pallas_stencil

    filt = filters.get_filter("blur3")
    import jax.numpy as jnp
    p = jnp.zeros((1, 38, 140), jnp.float32)
    with pytest.raises(ValueError, match="block_off"):
        pallas_stencil.fused_iterate_pallas(
            p, jnp.zeros((2,), jnp.int32), filt, 3, (32, 134),
            tile=(8, 128), interior_split=True)


def test_interior_split_geometry_fuzz():
    # Seeded sweep: 8 grids x alternating radius x random fuse, block
    # sizes, pad-rim shaves, and kernel tiles.  The class-based split
    # must stay bit-identical to the unsplit fused run everywhere —
    # including depth-vs-block edge cases, pad-rim devices, and
    # geometries where some or all classes have no interior tiles.
    # Guards the conservative middle-band box math beyond the
    # hand-picked cases above.
    rng = np.random.default_rng(1234)
    filts = [filters.get_filter("blur3"), filters.get_filter("gaussian5")]
    tiles = [(8, 128), (16, 128), (8, 256), (24, 128)]
    for trial in range(8):
        filt = filts[trial % 2]
        r = filt.radius
        grid = [(1, 2), (2, 1), (2, 2), (2, 4), (4, 2), (1, 4),
                (4, 1), (2, 3)][trial]
        tile = tiles[trial % 4]
        fuse = int(rng.integers(2, 4))
        depth = r * fuse
        # Blocks must fit the fused halo; keep shapes small but awkward.
        bh = depth + int(rng.integers(2, 40))
        bw = depth + int(rng.integers(2, 170))
        H = bh * grid[0] - int(rng.integers(0, min(bh - depth, 3) + 1))
        W = bw * grid[1] - int(rng.integers(0, min(bw - depth, 3) + 1))
        img = imageio.generate_test_image(H, W, "grey", seed=100 + trial)
        x = imageio.interleaved_to_planar(img).astype(np.float32)
        m = _mesh(grid)
        kw = dict(quantize=True, backend="pallas", fuse=fuse, tile=tile)
        base = step.sharded_iterate(x, filt, fuse * 2, mesh=m, **kw)
        split = step.sharded_iterate(x, filt, fuse * 2, mesh=m,
                                     interior_split=True, **kw)
        np.testing.assert_array_equal(
            np.asarray(base), np.asarray(split),
            err_msg=f"trial {trial}: grid={grid} HxW={H}x{W} "
                    f"filt={filt.name} fuse={fuse} tile={tile}")


def test_interior_range_sound_over_offset_classes():
    # Pure-math soundness fuzz, independent of the kernels: for ANY
    # geometry and ANY concrete device offset inside a class's (lo, hi)
    # range, every tile inside the box _interior_range returns must have
    # its level-0 window fully inside the image — the property that makes
    # skipping its ghost-ring masks an identity.  200 random points.
    from parallel_convolution_tpu.ops.pallas_stencil import (
        _interior_range, axis_offset_classes)

    rng = np.random.default_rng(7)
    boxes = 0
    for _ in range(200):
        th = 8 * int(rng.integers(1, 24))
        tw = 128 * int(rng.integers(1, 6))
        depth = int(rng.integers(1, 80))
        n_r = int(rng.integers(1, 5))
        n_c = int(rng.integers(1, 5))
        bh = depth + int(rng.integers(1, 2048))
        bw = depth + int(rng.integers(1, 2048))
        H = bh * n_r - int(rng.integers(0, min(bh - depth, 64) + 1))
        W = bw * n_c - int(rng.integers(0, min(bw - depth, 64) + 1))
        gh, gw = -(-bh // th), -(-bw // tw)
        for rcls in axis_offset_classes(n_r, bh):
            for ccls in axis_offset_classes(n_c, bw):
                box = _interior_range((H, W), (th, tw), depth, (gh, gw),
                                      (rcls, ccls))
                if box is None:
                    continue
                boxes += 1
                (i_lo, i_hi), (j_lo, j_hi) = box
                # Check the EXTREME offsets of the class range; interior-
                # ness is monotone in the offset, so ends suffice — but
                # test a midpoint too in case that assumption rots.
                r_offs = {rcls[0], rcls[1], (rcls[0] + rcls[1]) // 2}
                c_offs = {ccls[0], ccls[1], (ccls[0] + ccls[1]) // 2}
                for r0 in r_offs:
                    for i in (i_lo, i_hi):
                        assert r0 + i * th - depth >= 0, (rcls, box)
                        assert r0 + i * th + th + depth <= H, (rcls, box)
                for c0 in c_offs:
                    for j in (j_lo, j_hi):
                        assert c0 + j * tw - depth >= 0, (ccls, box)
                        assert c0 + j * tw + tw + depth <= W, (ccls, box)
    # Anti-vacuity: a regression that collapses every box to None must
    # fail here, not silently skip all 200 trials.
    assert boxes > 50, f"only {boxes} non-None boxes across the sweep"
