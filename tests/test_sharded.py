"""Distributed semantics on the forced 8-device CPU backend.

The upgrade over the reference's test story (SURVEY.md §4): halo exchange,
corner propagation, non-divisible shapes and convergence reductions are all
exercised without a cluster, and outputs are required to be bit-identical to
the serial NumPy oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.parallel import step
from parallel_convolution_tpu.utils import imageio

MESH_SHAPES = [(1, 1), (1, 2), (2, 2), (4, 2), (2, 4), (8, 1), (1, 8)]


def _mesh(shape):
    n = shape[0] * shape[1]
    return mesh_lib.make_grid_mesh(jax.devices()[:n], shape)


def _run_sharded_u8(img_u8, filt, iters, mshape, backend="shifted"):
    x = imageio.interleaved_to_planar(img_u8).astype(np.float32)
    out = step.sharded_iterate(x, filt, iters, mesh=_mesh(mshape),
                               quantize=True, backend=backend)
    return imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))


def test_dims_create():
    assert mesh_lib.dims_create(8) == (2, 4)
    assert mesh_lib.dims_create(16) == (4, 4)
    assert mesh_lib.dims_create(7) == (1, 7)
    assert mesh_lib.dims_create(1) == (1, 1)
    assert mesh_lib.dims_create(12) == (3, 4)


@pytest.mark.parametrize("mshape", MESH_SHAPES)
def test_blur_bitexact_all_mesh_shapes(grey_odd, mshape):
    # 37×53 does not divide evenly by any of these grids → exercises padding
    # + masking alongside the halo exchange.
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 5)
    got = _run_sharded_u8(grey_odd, filt, 5, mshape)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mshape", [(2, 2), (2, 4)])
def test_rgb_bitexact(rgb_odd, mshape):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(rgb_odd, filt, 4)
    got = _run_sharded_u8(rgb_odd, filt, 4, mshape)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", ["gaussian5", "edge5"])
def test_radius2_halo_bitexact(grey_odd, name):
    # 5×5 filters need 2-wide halos: corners require values two hops away,
    # the strongest test of two-phase corner propagation.
    filt = filters.get_filter(name)
    want = oracle.run_serial_u8(grey_odd, filt, 3)
    got = _run_sharded_u8(grey_odd, filt, 3, (2, 4))
    np.testing.assert_array_equal(got, want)


def test_xla_conv_backend_sharded(grey_small):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_small, filt, 10)
    got = _run_sharded_u8(grey_small, filt, 10, (2, 2), backend="xla_conv")
    np.testing.assert_array_equal(got, want)


def test_sharded_equals_single_device(grey_odd):
    # property: shard(conv(x)) == conv(shard(x))
    filt = filters.get_filter("sharpen3")
    a = _run_sharded_u8(grey_odd, filt, 6, (1, 1))
    b = _run_sharded_u8(grey_odd, filt, 6, (4, 2))
    np.testing.assert_array_equal(a, b)


def test_hlo_contains_collective_permute(grey_small):
    # Guard against the halo silently materializing as all-gather
    # (SURVEY.md §2 'assert-in-HLO' requirement).
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    xs, valid_hw, block_hw = step._prepare(
        imageio.interleaved_to_planar(grey_small).astype(np.float32), m, 1
    )
    fn = step._build_iterate(m, filt, 3, True, valid_hw, block_hw, "shifted")
    hlo = fn.lower(xs).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo


def test_convergence_identity_immediate(grey_small):
    filt = filters.get_filter("identity3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out, done = step.sharded_converge(x, filt, tol=1e-6, max_iters=100,
                                      check_every=4, mesh=_mesh((2, 2)))
    assert done == 4
    np.testing.assert_array_equal(
        imageio.planar_to_interleaved(np.asarray(out)), grey_small.astype(np.float32)
    )


def test_convergence_matches_oracle_jacobi():
    filt = filters.get_filter("jacobi3")
    img = imageio.generate_test_image(32, 48, "grey", seed=11).astype(np.float32)
    want, want_iters = oracle.run_to_convergence_f32(
        img, filt, tol=0.05, max_iters=500, check_every=10
    )
    x = img[None]
    got, got_iters = step.sharded_converge(
        x, filt, tol=0.05, max_iters=500, check_every=10, mesh=_mesh((2, 4))
    )
    assert got_iters == want_iters
    np.testing.assert_array_equal(np.asarray(got)[0], want)


@pytest.mark.parametrize("fuse,check_every", [(4, 10), (3, 10), (10, 10),
                                              (4, 3)])
def test_convergence_fused_matches_unfused(fuse, check_every):
    """fuse>1 in the convergence path: identical iters + bit-identical
    result for any (fuse, check_every) combination, including fuse >
    check_every (clamped) and non-divisible remainders."""
    filt = filters.get_filter("jacobi3")
    img = imageio.generate_test_image(32, 48, "grey", seed=3).astype(np.float32)
    x = img[None]
    want, want_iters = step.sharded_converge(
        x, filt, tol=0.05, max_iters=200, check_every=check_every,
        mesh=_mesh((2, 2)))
    got, got_iters = step.sharded_converge(
        x, filt, tol=0.05, max_iters=200, check_every=check_every,
        mesh=_mesh((2, 2)), fuse=fuse)
    assert got_iters == want_iters
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_convergence_fused_pallas_tile(grey_small):
    """Pallas backend + explicit tile through the convergence path."""
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    want, want_iters = step.sharded_converge(
        x, filt, tol=0.5, max_iters=60, check_every=5, mesh=_mesh((2, 2)),
        quantize=True)
    got, got_iters = step.sharded_converge(
        x, filt, tol=0.5, max_iters=60, check_every=5, mesh=_mesh((2, 2)),
        quantize=True, backend="pallas_sep", fuse=4, tile=(16, 128))
    assert got_iters == want_iters
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_iterate_tile_override_bit_identical(grey_odd):
    """sharded_iterate's public tile knob: any tile is bit-identical."""
    filt = filters.get_filter("blur3")
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    want = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                                backend="pallas")
    got = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               backend="pallas", tile=[8, 128])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    with pytest.raises(ValueError, match="positive"):
        step.sharded_iterate(x, filt, 1, mesh=_mesh((2, 2)),
                             backend="pallas", tile=(0, 128))


def test_convergence_hits_max_iters(grey_small):
    # float-mode jacobi on noise shrinks diffs slowly: far from 1e-9 in 7
    # iterations, so the loop must run the full 3+3+1 chunk schedule.
    filt = filters.get_filter("jacobi3")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    _, done = step.sharded_converge(x, filt, tol=1e-9, max_iters=7,
                                    check_every=3, mesh=_mesh((2, 2)),
                                    quantize=False)
    assert done == 7  # chunks of 3,3,1 — the min() remainder path


def test_block_smaller_than_radius_raises():
    filt = filters.get_filter("gaussian5")
    tiny = np.ones((1, 8, 3), np.float32)  # W blocks of 1 < radius 2 on 1×4
    with pytest.raises(ValueError, match="smaller than filter radius"):
        step.sharded_iterate(tiny, filt, 1, mesh=_mesh((1, 4)))


def test_mesh_interpret_resolves_from_mesh_devices():
    # One process can hold a TPU default backend AND a forced-CPU mesh
    # (the driver's entry() + dryrun_multichip sequence); interpret= must
    # come from the mesh's own devices, not jax.devices() — a CPU mesh
    # always interprets, and a device reporting a TPU kind never does.
    # (Platform-agnostic: under PCTPU_TEST_PLATFORM=tpu the real mesh is
    # a TPU one and the expectation flips.)
    from parallel_convolution_tpu.utils.platform import device_on_tpu

    devs = jax.devices()
    m = mesh_lib.make_grid_mesh(devs[: min(4, len(devs))])
    assert step._mesh_interpret(m) is (not device_on_tpu(devs[0]))

    class FakeTpuDevice:
        platform = "axon"
        device_kind = "TPU v5 lite"

    class FakeMesh:
        devices = np.asarray([[FakeTpuDevice()]])

    assert step._mesh_interpret(FakeMesh()) is False


@pytest.mark.parametrize("mshape", [(1, 1), (2, 2)])
def test_converge_interior_split_bitexact(mshape):
    # The convergence path's fused chunks accept the interior split too
    # (any grid since round 5); iterate count and bytes must match the
    # unsplit run exactly.
    img = imageio.generate_test_image(45, 300, "grey", seed=23)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    filt = filters.get_filter("jacobi3")
    m = mesh_lib.make_grid_mesh(
        jax.devices()[: mshape[0] * mshape[1]], mshape)
    kw = dict(tol=0.05, max_iters=40, check_every=5, mesh=m,
              backend="pallas_sep", fuse=3, tile=(8, 128))
    out_a, it_a = step.sharded_converge(x, filt, **kw)
    out_b, it_b = step.sharded_converge(x, filt, interior_split=True, **kw)
    assert it_a == it_b
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
