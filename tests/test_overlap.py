"""Overlapped halo pipeline: byte-identity, resolution, and cost model.

The interior-first restructure of the RDMA kernels (``overlap=True``)
must be byte-identical to the serialized order everywhere — the only
thing it may change is WHEN independent pixels compute relative to the
in-flight ghost DMAs.  Three proof tiers:

* degenerate grids (any jax): extent-1 axes statically elide every RDMA
  construct, so the monolithic kernel's interior/band REGION-SPLIT
  compute — the overlap path's only new math when no DMA exists — is
  pinned against both the serialized twin and the oracle;
* the full multi-device protocol (2x4 / 2x2 / 1-long-axis meshes, both
  kernels) under the DMA-faithful TPU interpreter — skips with cause on
  a jax without it, exactly like tests/test_rdma.py;
* the resolution layer: the knob is a clamped request (RDMA tier only,
  force-serialized under interpreted Pallas unless the byte-proof env
  hatch is set), and every row stamps the RESOLVED value.

Plus drift guards pinning the cost model's overlap term
(max(compute, exchange) replacing compute + exchange when legal) so the
constants ``backend="auto"`` ranks with cannot silently drift from the
kernels' legality rules.
"""

import warnings

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio, jax_compat

needs_faithful_interpret = pytest.mark.skipif(
    not jax_compat.HAS_TPU_INTERPRET,
    reason="DMA-faithful TPU interpret mode unavailable in this jax "
           "(needs current jax, or real silicon)")


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _run(img, filt, iters, mesh_shape, *, boundary="zero", fuse=1,
         overlap=False, storage=np.float32, tiled=None, tile=None):
    """Chained fused_rdma_step invocations straight at the kernel (the
    dispatch layer's interpret clamp deliberately bypassed: this file
    proves the overlapped PROGRAM's bytes)."""
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    mesh = _mesh(mesh_shape)
    x = imageio.interleaved_to_planar(img).astype(storage)
    valid_hw = None if boundary == "periodic" else img.shape[:2]
    n = iters // fuse

    def body(v):
        import jax.lax as lax

        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, mesh_shape, boundary, quantize=True,
                tiled=tiled, tile=tile, fuse=fuse, valid_hw=valid_hw,
                overlap=overlap)
        return lax.fori_loop(0, n, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    return np.asarray(out)[0].astype(np.uint8)


# ---------------------------------------------------------------------------
# Region partition unit (the geometry both the kernel and the cost model's
# legality predicate rely on).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h,w,d", [(32, 48, 2), (8, 8, 4), (5, 40, 2),
                                   (3, 3, 2), (16, 4, 1), (1, 1, 1),
                                   (64, 64, 8)])
def test_overlap_regions_partition_exact(h, w, d):
    """The interior/row-band/col-band rectangles tile the (h, w) block
    exactly — every output pixel exactly once, any geometry."""
    from parallel_convolution_tpu.ops.pallas_rdma import overlap_regions

    interior, row_bands, col_bands = overlap_regions(h, w, d)
    cover = np.zeros((h, w), np.int32)
    for (r0, r1, c0, c1) in interior + row_bands + col_bands:
        assert 0 <= r0 < r1 <= h and 0 <= c0 < c1 <= w
        cover[r0:r1, c0:c1] += 1
    np.testing.assert_array_equal(cover, np.ones((h, w), np.int32))
    # Interior is exactly the ghost-free box, empty when the block is
    # all rim (the cost model's overlap_legal condition).
    if min(h, w) > 2 * d:
        assert interior == [(d, h - d, d, w - d)]
    else:
        assert interior == []


def test_overlap_legal_mirrors_regions():
    """costmodel.overlap_legal == "interior non-empty on an RDMA tier
    with a collective" — drift-guarded against the kernel's partition."""
    from parallel_convolution_tpu.ops.pallas_rdma import overlap_regions
    from parallel_convolution_tpu.tuning import costmodel

    for block in ((32, 32), (8, 8), (4, 64), (2, 2)):
        for r, T in ((1, 1), (1, 4), (2, 2)):
            want = bool(overlap_regions(block[0], block[1], r * T)[0])
            assert costmodel.overlap_legal(
                "pallas_rdma", (2, 2), block, r, T) == want
    # Never for non-RDMA tiers or a 1x1 grid.
    assert not costmodel.overlap_legal("pallas", (2, 2), (64, 64), 1, 1)
    assert not costmodel.overlap_legal("shifted", (2, 2), (64, 64), 1, 1)
    assert not costmodel.overlap_legal("pallas_rdma", (1, 1), (64, 64), 1, 1)


# ---------------------------------------------------------------------------
# Degenerate grids: the region-split compute on any jax.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fuse", [1, 2, 4])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
def test_overlap_degenerate_monolithic(fuse, boundary):
    """1x1 grid, overlap=True: the 5-region interior-first compute must
    equal the serialized whole-block program AND the oracle, both
    boundaries, fuse 1/2/4 (odd dims exercise the pad-rim masking)."""
    filt = filters.get_filter("blur3")
    dims = (24, 36) if boundary == "periodic" else (37, 53)
    img = imageio.generate_test_image(*dims, "grey", seed=41)
    iters = 4 * fuse
    want = oracle.run_serial_u8(img, filt, iters, boundary=boundary)
    ov = _run(img, filt, iters, (1, 1), boundary=boundary, fuse=fuse,
              overlap=True)
    ser = _run(img, filt, iters, (1, 1), boundary=boundary, fuse=fuse,
               overlap=False)
    np.testing.assert_array_equal(ov, ser)
    np.testing.assert_array_equal(ov, want)


def test_overlap_degenerate_monolithic_radius2_u8():
    """radius-2 taps + u8 carry through the region split (deep rim)."""
    filt = filters.get_filter("gaussian5")
    img = imageio.generate_test_image(41, 57, "grey", seed=42)
    ov = _run(img, filt, 4, (1, 1), fuse=2, overlap=True,
              storage=np.uint8)
    want = oracle.run_serial_u8(img, filt, 4)
    np.testing.assert_array_equal(ov, want)


def test_overlap_degenerate_block_all_rim():
    """A block smaller than 2*d on one axis: interior empties out and
    the bands absorb everything — still byte-exact."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(7, 64, "grey", seed=43)
    ov = _run(img, filt, 3, (1, 1), fuse=3, overlap=True)
    want = oracle.run_serial_u8(img, filt, 3)
    np.testing.assert_array_equal(ov, want)


@pytest.mark.parametrize("fuse", [2, 4])
def test_overlap_degenerate_tiled(fuse):
    """Tiled kernel with overlap=True on 1x1: no remote axis exists, so
    the program is the serialized one verbatim — pinned byte-exact."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(96, 384, "grey", seed=44)
    ov = _run(img, filt, 2 * fuse, (1, 1), fuse=fuse, overlap=True,
              tiled=True, tile=(32, 128))
    ser = _run(img, filt, 2 * fuse, (1, 1), fuse=fuse, overlap=False,
               tiled=True, tile=(32, 128))
    want = oracle.run_serial_u8(img, filt, 2 * fuse)
    np.testing.assert_array_equal(ov, ser)
    np.testing.assert_array_equal(ov, want)


# ---------------------------------------------------------------------------
# Full protocol (faithful interpreter / silicon only): overlap ==
# serialized == oracle on real multi-device grids, both kernels.
# ---------------------------------------------------------------------------


@needs_faithful_interpret
@pytest.mark.parametrize("mesh_shape", [(2, 4), (2, 2), (1, 8), (4, 1)])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
def test_overlap_monolithic_protocol(mesh_shape, boundary):
    """Interior-first under REAL (simulated) in-flight DMAs: 2x4 / 2x2 /
    1-long-axis grids, both boundaries — overlap == serialized ==
    oracle.  The 1-long-axis grids pin the statically-elided-axis forms
    (row-only / col-only exchange under the pipeline)."""
    filt = filters.get_filter("blur3")
    if boundary == "periodic":
        dims = (mesh_shape[0] * 16, mesh_shape[1] * 16)
    else:
        dims = (mesh_shape[0] * 16 + 5, mesh_shape[1] * 16 + 3)
    img = imageio.generate_test_image(*dims, "grey", seed=45)
    for fuse in (1, 2, 4):
        iters = 2 * fuse
        want = oracle.run_serial_u8(img, filt, iters, boundary=boundary)
        ov = _run(img, filt, iters, mesh_shape, boundary=boundary,
                  fuse=fuse, overlap=True)
        ser = _run(img, filt, iters, mesh_shape, boundary=boundary,
                   fuse=fuse, overlap=False)
        np.testing.assert_array_equal(ov, ser)
        np.testing.assert_array_equal(ov, want)


@needs_faithful_interpret
@pytest.mark.parametrize("fuse", [1, 2, 4])
def test_overlap_tiled_protocol(fuse):
    """Tiled kernel on 2x2: rotated rim-last traversal + deferred
    semaphore waits must reproduce the serialized bytes exactly."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(64, 256, "grey", seed=46)
    ov = _run(img, filt, 2 * fuse, (2, 2), fuse=fuse, overlap=True,
              tiled=True, tile=(16, 128))
    ser = _run(img, filt, 2 * fuse, (2, 2), fuse=fuse, overlap=False,
               tiled=True, tile=(16, 128))
    want = oracle.run_serial_u8(img, filt, 2 * fuse)
    np.testing.assert_array_equal(ov, ser)
    np.testing.assert_array_equal(ov, want)


@needs_faithful_interpret
def test_overlap_monolithic_race_detector(grey_small):
    """The interpreter's vector-clock race detector over the overlapped
    protocol: interior/band reads vs in-flight ghost writes must be
    provably ordered (disjoint or semaphore-separated) on every pair."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)[
        :, :24, :36]
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        import jax.lax as lax

        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, (2, 2), "zero", quantize=True, interpret=params,
                fuse=2, valid_hw=(24, 36), overlap=True)
        return lax.fori_loop(0, 2, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(x[0].astype(np.uint8), filt, 4)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


# ---------------------------------------------------------------------------
# Resolution: the knob is a clamped request; rows stamp the RESOLVED value.
# ---------------------------------------------------------------------------


def test_resolve_overlap_clamps(monkeypatch):
    monkeypatch.delenv(step.OVERLAP_INTERPRET_ENV, raising=False)
    mesh = _mesh((2, 4))
    assert step.resolve_overlap(None, "pallas_rdma", mesh) is False
    assert step.resolve_overlap(False, "pallas_rdma", mesh) is False
    # Non-RDMA backend: force-serialized with a one-time warning.
    step._OVERLAP_WARNED.clear()
    with pytest.warns(UserWarning, match="no overlapped halo pipeline"):
        assert step.resolve_overlap(True, "shifted", mesh) is False
    # Interpreted mesh: force-serialized with a one-time warning...
    with pytest.warns(UserWarning, match="force-serialized"):
        assert step.resolve_overlap(True, "pallas_rdma", mesh) is False
    # ...warn-once: the second request is silent (same cause).
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert step.resolve_overlap(True, "pallas_rdma", mesh) is False
    # The byte-proof env hatch engages the overlapped program anyway.
    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    assert step.resolve_overlap(True, "pallas_rdma", mesh) is True


def test_bench_row_stamps_resolved_overlap(monkeypatch):
    """bench_iterate rows stamp the knob the executable was ACTUALLY
    compiled with — True only when the request survives every clamp."""
    from parallel_convolution_tpu.utils import bench

    filt = filters.get_filter("blur3")
    step._OVERLAP_WARNED.clear()
    monkeypatch.delenv(step.OVERLAP_INTERPRET_ENV, raising=False)
    with pytest.warns(UserWarning):
        row = bench.bench_iterate((16, 128), filt, 2, mesh=_mesh((1, 1)),
                                  backend="pallas_rdma", reps=1,
                                  overlap=True)
    assert row["overlap"] is False  # interpret clamp
    assert row["exchange_hidden_fraction"] == 0.0
    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    row = bench.bench_iterate((16, 128), filt, 2, mesh=_mesh((1, 1)),
                              backend="pallas_rdma", reps=1, overlap=True)
    assert row["overlap"] is True
    assert row["effective_backend"] == "pallas_rdma"
    # Serialized rows are unchanged in shape: the knob is always present.
    row = bench.bench_iterate((16, 64), filt, 2, mesh=_mesh((1, 1)),
                              backend="shifted", reps=1)
    assert row["overlap"] is False


def test_driver_overlap_bytes_via_dispatch(monkeypatch):
    """The full dispatch stack (sharded_iterate -> resolve_overlap ->
    _build_iterate) drives the overlapped program under the env hatch,
    byte-exact vs the serialized run and the oracle."""
    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(37, 53, "grey", seed=47)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    mesh = _mesh((1, 1))
    outs = {}
    for ov in (False, True):
        out = step.sharded_iterate(x, filt, 6, mesh=mesh, quantize=True,
                                   backend="pallas_rdma", fuse=2,
                                   overlap=ov)
        outs[ov] = imageio.planar_to_interleaved(
            np.asarray(out).astype(np.uint8))
    want = oracle.run_serial_u8(img, filt, 6)
    np.testing.assert_array_equal(outs[True], outs[False])
    np.testing.assert_array_equal(outs[True], want)


def test_probe_key_distinguishes_overlap():
    """The degrade probe cache keys on the overlap form: the overlapped
    RDMA program is a different kernel than the serialized one."""
    from parallel_convolution_tpu.resilience import degrade

    filt = filters.get_filter("blur3")
    mesh = _mesh((1, 1))
    k1 = degrade._probe_key(mesh, filt, "pallas_rdma", True, 1, "zero",
                            None, False, "f32", (8, 8), overlap=False)
    k2 = degrade._probe_key(mesh, filt, "pallas_rdma", True, 1, "zero",
                            None, False, "f32", (8, 8), overlap=True)
    assert k1 != k2


# ---------------------------------------------------------------------------
# Cost model drift guards: the overlap term's constants.
# ---------------------------------------------------------------------------


def test_predict_overlap_is_max_not_sum():
    """The overlap factor: max(compute, exchange) replaces
    compute + exchange exactly — pinned by recomputing both sides from
    the model's own components."""
    from parallel_convolution_tpu.tuning import costmodel as cm

    hw = cm.TPU_V5E
    backend, storage, fuse, tile = "pallas_rdma", "f32", 4, None
    shape, block, grid, k = (1, 4096, 4096), (2048, 1024), (2, 4), 3
    radius = 1
    t_hbm = cm.hbm_bytes_per_px_iter(backend, storage, fuse, tile, block,
                                     radius, shape) / (hw.hbm_gbps * 1e9)
    t_flop = cm.flops_per_px_iter(k, False, True, fuse, block,
                                  radius) / (hw.flop_gops * 1e9)
    # The RDMA tier binds persistent channels (round 16): its exchange
    # term zeroes the per-phase setup and prices the packed column
    # transport — recompute the SAME term predict uses.
    t_ex = cm.exchange_seconds_per_px_iter(grid, block, radius, fuse,
                                           storage, hw, persistent=True,
                                           col_mode="packed")
    assert t_ex > 0
    serial = cm.predict_seconds_per_px_iter(
        backend, storage, fuse, tile, shape, block, grid, k, False, True,
        hw)
    overlapped = cm.predict_seconds_per_px_iter(
        backend, storage, fuse, tile, shape, block, grid, k, False, True,
        hw, overlap=True)
    assert serial == pytest.approx(max(t_hbm, t_flop) + t_ex, rel=1e-12)
    assert overlapped == pytest.approx(max(max(t_hbm, t_flop), t_ex),
                                       rel=1e-12)
    assert overlapped <= serial
    # Illegal overlap (1x1 grid / wrong tier) silently prices serialized.
    assert cm.predict_seconds_per_px_iter(
        backend, storage, fuse, tile, shape, block, (1, 1), k, False,
        True, hw, overlap=True) == cm.predict_seconds_per_px_iter(
        backend, storage, fuse, tile, shape, block, (1, 1), k, False,
        True, hw)
    assert cm.predict_seconds_per_px_iter(
        "pallas", storage, fuse, tile, shape, block, grid, k, False,
        True, hw, overlap=True) == cm.predict_seconds_per_px_iter(
        "pallas", storage, fuse, tile, shape, block, grid, k, False,
        True, hw)


def test_candidate_space_overlap_variants(monkeypatch):
    """Enumeration: overlap variants exist only for the RDMA tier where
    legal; a pinned False yields none; ranking never prefers the
    overlapped form on a model tie."""
    from parallel_convolution_tpu.tuning import search
    from parallel_convolution_tpu.tuning.plans import Workload

    filt = filters.get_filter("blur3")
    w = Workload.from_mesh(_mesh((2, 4)), filt, (1, 512, 512))
    # Interpreted-Pallas platform (this CPU mesh) without the byte-proof
    # hatch: NO overlap candidates — dispatch would force-serialize them,
    # so the tuner must not measure (or persist) a form that never runs.
    monkeypatch.delenv(step.OVERLAP_INTERPRET_ENV, raising=False)
    assert not [c for c in search.enumerate_candidates(w) if c.overlap]
    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    cands = search.enumerate_candidates(w)
    rdma_ov = [c for c in cands if c.overlap]
    assert rdma_ov and all(c.backend == "pallas_rdma" for c in rdma_ov)
    assert not [c for c in search.enumerate_candidates(w, overlap=False)
                if c.overlap]
    # overlap=True request: RDMA candidates all overlapped, other tiers
    # clamp to serialized rather than emptying the space.
    pinned = search.enumerate_candidates(w, overlap=True)
    assert all(c.overlap == (c.backend == "pallas_rdma") for c in pinned)
    # Tie-break: zero-exchange workload (1x1) enumerates no overlap at
    # all, so serialized always wins flat ties by construction.
    w1 = Workload.from_mesh(_mesh((1, 1)), filt, (1, 64, 64))
    assert not [c for c in search.enumerate_candidates(w1) if c.overlap]


def test_plan_record_overlap_roundtrip(tmp_path):
    """Plans persist the overlap verdict; legacy records (no key) load
    as serialized — the exact pre-overlap behavior, no schema bump."""
    from parallel_convolution_tpu.tuning.plans import Plan, PlanCache, Workload

    filt = filters.get_filter("blur3")
    w = Workload.from_mesh(_mesh((2, 4)), filt, (1, 512, 512))
    cache = PlanCache()
    cache.put(w, Plan("pallas_rdma", fuse=4, overlap=True,
                      source="measured"))
    p = str(tmp_path / "plans.json")
    cache.save(p)
    loaded = PlanCache.load(p)
    plan = loaded.exact(w)
    assert plan is not None and plan.overlap is True
    # Legacy record: strip the key as an old tuner would have written it.
    rec = loaded.records[w.key()]
    rec.pop("overlap")
    assert Plan.from_record(rec).overlap is False


def test_resolve_overlap_from_plan():
    """backend='auto' with an armed plan resolves the stored overlap
    verdict (clamped to the workload's legality) and stamps provenance."""
    from parallel_convolution_tpu import tuning
    from parallel_convolution_tpu.tuning.plans import Plan, PlanCache, Workload

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 4))
    w = Workload.from_mesh(mesh, filt, (1, 512, 512))
    cache = PlanCache()
    cache.put(w, Plan("pallas_rdma", fuse=4, overlap=True,
                      source="measured"))
    res = tuning.resolve(mesh, filt, (1, 512, 512), plans=cache)
    assert (res.backend, res.fuse, res.overlap) == ("pallas_rdma", 4, True)
    assert res.source == "measured"
    # An explicit overlap=False request overrides the plan's verdict.
    res = tuning.resolve(mesh, filt, (1, 512, 512), plans=cache,
                         overlap=False)
    assert res.overlap is False
    # A pinned fuse that kills the interior re-clamps the stored True:
    # blocks 256x128, fuse=32 -> d=32, 2*d < 128 still legal; use a
    # small image instead so the whole block is rim.
    w2 = Workload.from_mesh(mesh, filt, (1, 8, 8))
    cache2 = PlanCache()
    cache2.put(w2, Plan("pallas_rdma", fuse=1, overlap=True,
                        source="measured"))
    res2 = tuning.resolve(mesh, filt, (1, 8, 8), plans=cache2)
    assert res2.overlap is False  # block 4x2: all rim, overlap illegal


# ---------------------------------------------------------------------------
# Attribution: hidden vs exposed exchange.
# ---------------------------------------------------------------------------


def test_exchange_split_serialized_matches_legacy_series():
    from parallel_convolution_tpu.obs import attribution

    kw = dict(backend="pallas_rdma", storage="f32", shape=(1, 512, 512),
              tile=None, quantize=True, separable=False, platform="tpu",
              device_kind="tpu-v5e")
    frac = attribution.predicted_exchange_fraction(
        (2, 4), (256, 128), 1, 4, **kw)
    split = attribution.predicted_exchange_split(
        (2, 4), (256, 128), 1, 4, **kw)
    assert split["exchange_fraction"] == frac
    assert split["exchange_hidden_fraction"] == 0.0
    assert split["overlap"] is False


def test_exchange_split_overlap_budget():
    """Overlap-adjusted split invariants: hidden + exposed == the whole
    exchange, exposed shrinks vs serialized, 1x1 grids are exactly 0."""
    from parallel_convolution_tpu.obs import attribution
    from parallel_convolution_tpu.tuning import costmodel as cm

    kw = dict(backend="pallas_rdma", storage="f32", shape=(1, 512, 512),
              tile=None, quantize=True, separable=False, platform="tpu",
              device_kind="tpu-v5e")
    grid, block, radius, fuse = (2, 4), (256, 128), 1, 4
    ser = attribution.predicted_exchange_split(grid, block, radius, fuse,
                                               **kw)
    ov = attribution.predicted_exchange_split(grid, block, radius, fuse,
                                              overlap=True, **kw)
    assert ov["overlap"] is True
    assert ov["exchange_fraction"] <= ser["exchange_fraction"]
    assert 0.0 <= ov["exchange_hidden_fraction"] <= 1.0
    # hidden/total + exposed/total == ex/total at the model's quantities.
    hw = cm.hardware_for("tpu", "tpu-v5e")
    ex = cm.exchange_seconds_per_px_iter(grid, block, radius, fuse,
                                         "f32", hw)
    total = ex / max(1e-30, (ov["exchange_fraction"]
                             + ov["exchange_hidden_of_total"]))
    assert total > 0  # consistency: the two shares reassemble the term
    z = attribution.predicted_exchange_split((1, 1), block, radius, fuse,
                                             overlap=True, **kw)
    assert z["exchange_fraction"] == z["exchange_hidden_fraction"] == 0.0


# ---------------------------------------------------------------------------
# Serving: the knob rides the key; responses stamp the resolved value.
# ---------------------------------------------------------------------------


def test_engine_key_carries_resolved_overlap(monkeypatch):
    from parallel_convolution_tpu.serving.engine import WarmEngine

    step._OVERLAP_WARNED.clear()
    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    eng = WarmEngine(mesh=_mesh((1, 1)))
    k_on, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma", iters=2,
                              overlap=True)
    k_off, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma", iters=2,
                               overlap=False)
    assert k_on.overlap is True and k_off.overlap is False
    assert k_on != k_off
    # None (absent) resolves False for explicit backends — the exact
    # pre-overlap key, so old clients share executables with new ones.
    k_def, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma", iters=2)
    assert k_def == k_off


def test_service_response_stamps_overlap(monkeypatch):
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request,
    )

    monkeypatch.setenv(step.OVERLAP_INTERPRET_ENV, "1")
    img = imageio.generate_test_image(16, 128, "grey", seed=48)
    svc = ConvolutionService(mesh=_mesh((1, 1)), max_delay_s=0.001)
    try:
        res = svc.submit(Request(image=img, iters=2,
                                 backend="pallas_rdma", overlap=True))
        assert res.ok
        assert res.overlap is True
        assert res.exchange_fraction == 0.0  # 1x1 grid: no exchange
        assert res.exchange_hidden_fraction == 0.0
        want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
        np.testing.assert_array_equal(res.image, want)
        res2 = svc.submit(Request(image=img, iters=2, backend="shifted"))
        assert res2.ok and res2.overlap is False
    finally:
        svc.close()
