"""Content-addressed result cache (round 22): keys, tiers, WAL journal.

The ISSUE 18 acceptance properties on the 8-virtual-device CPU mesh:

* key contract — result keys fold the full compile identity (iters
  changes the bytes, so it changes the key); converge keys fold the
  fixed point's identity (tol/solver/mg_levels) but NOT max_iters/
  check_every, which only change reporting cadence;
* two tiers — memory LRU spills to CRC-validated content-addressed
  disk files; a corrupt disk entry is a loud journaled-dead miss,
  never served bytes; a memory-only eviction IS a journaled death;
* never-resurrect — deaths are journaled write-ahead through the WAL's
  ``cache`` record kind; a cache rebuilt over a recovered
  ``WALState.cache_dead`` refuses surviving bytes, and a re-store
  journals ``live`` to lift the tombstone;
* service integration — duplicate submits are served stamped
  ``cache: "hit"`` with the engine's compile/batch/image counters
  exactly flat, byte-identical to the oracle; the cache is OFF unless
  injected (existing batching semantics unchanged);
* shared-evidence IO — the one sanctioned curve writer preserves
  foreign lanes both ways, and the static gate demonstrably catches a
  direct open-for-write of a shared curve.
"""

from __future__ import annotations

import importlib.util
import json
import threading
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.resilience import diskio, faults

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.serving import wal as wal_mod
from parallel_convolution_tpu.serving.cache import (
    ResultCache, converge_key, input_digest, result_key,
)
from parallel_convolution_tpu.serving.engine import WarmEngine
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Request,
)
from parallel_convolution_tpu.utils import imageio
from parallel_convolution_tpu.utils.evidence_io import rewrite_shared_jsonl

SCRIPTS = Path(__file__).resolve().parent.parent / "scripts"


@pytest.fixture(autouse=True)
def _clean_fault_state():
    yield
    faults.uninstall_plan()
    diskio.uninstall_modes()


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=24, cols=32, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


_KEY_ENGINE: list = []


def _key(img, **kw):
    kw.setdefault("filter_name", "blur3")
    kw.setdefault("iters", 2)
    if not _KEY_ENGINE:
        _KEY_ENGINE.append(WarmEngine(_mesh()))   # key math only
    return _KEY_ENGINE[0].key_for((1,) + img.shape, **kw)


def _arrays(seed=0, n=64):
    rng = np.random.default_rng(seed)
    return {"image": rng.integers(0, 255, (1, 4, n // 4),
                                  dtype=np.uint8)}


# ------------------------------------------------------------- keys


def test_input_digest_covers_dtype_shape_and_bytes():
    a = np.arange(64, dtype=np.uint8).reshape(1, 8, 8)
    assert input_digest(a) == input_digest(a.copy())
    assert input_digest(a) != input_digest(a.reshape(8, 8, 1))
    assert input_digest(a) != input_digest(a.astype(np.uint16))
    b = a.copy()
    b[0, 0, 0] ^= 1
    assert input_digest(a) != input_digest(b)


def test_result_key_folds_compile_identity():
    img = _img()
    d = input_digest(img[None])
    assert result_key(d, _key(img)) == result_key(d, _key(img))
    assert result_key(d, _key(img)) != result_key(d, _key(img, iters=3))
    assert (result_key(d, _key(img))
            != result_key(d, _key(img, filter_name="sharpen3")))


def test_converge_key_is_fixed_point_identity_not_budget():
    img = _img()
    d = input_digest(img[None])
    k1 = _key(img, iters=10)
    k2 = _key(img, iters=50)   # check_every cadence rides in iters
    assert (converge_key(d, tol=1e-3, solver="jacobi", mg_levels=None,
                         engine_key=k1)
            == converge_key(d, tol=1e-3, solver="jacobi", mg_levels=None,
                            engine_key=k2))
    assert (converge_key(d, tol=1e-3, solver="jacobi", mg_levels=None)
            != converge_key(d, tol=1e-4, solver="jacobi", mg_levels=None))
    assert (converge_key(d, tol=1e-3, solver="jacobi", mg_levels=None)
            != converge_key(d, tol=1e-3, solver="multigrid", mg_levels=3))
    # Converge and batch keys for the same digest never collide.
    assert converge_key(d, tol=1e-3, solver="jacobi",
                        mg_levels=None) != result_key(d, k1)


# ------------------------------------------------------------- tiers


def test_put_get_round_trip_copies_caller_buffer():
    c = ResultCache()
    arrs = _arrays()
    orig = arrs["image"].copy()
    c.put("k1", arrs, {"m": 1})
    arrs["image"][:] = 0          # caller reuses its buffer
    got = c.get("k1")
    assert got is not None
    np.testing.assert_array_equal(got[0]["image"], orig)
    assert got[1] == {"m": 1}
    assert c.get("nope") is None
    s = c.snapshot()
    assert s["hits_mem"] == 1 and s["misses"] == 1 and s["stores"] == 1


def test_memory_only_eviction_is_journaled_death():
    journal = []
    c = ResultCache(capacity_entries=2,
                    journal=lambda op, k: journal.append((op, k)))
    for i in range(3):
        c.put(f"k{i}", _arrays(i), {})
    assert c.get("k0") is None            # LRU victim, no disk tier
    assert ("dead", "k0") in journal
    assert c.stats["evictions"] == 1
    # The tombstone means even a racing writer's bytes can't revive it
    # without a live record.
    c.put("k0", _arrays(0), {})
    assert ("live", "k0") in journal
    assert c.get("k0") is not None


def test_disk_spill_promote_and_crc_corruption(tmp_path):
    journal = []
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc",
                    journal=lambda op, k: journal.append((op, k)))
    a0, a1 = _arrays(0), _arrays(1)
    c.put("k0", a0, {"who": "k0"})
    c.put("k1", a1, {"who": "k1"})       # spills k0 to disk
    assert c.stats["spills"] == 1
    files = list((tmp_path / "rc").glob("*.rc"))
    assert [f.name for f in files] == ["k0.rc"]
    got = c.get("k0")                     # disk hit, promoted
    assert got is not None and got[1] == {"who": "k0"}
    np.testing.assert_array_equal(got[0]["image"], a0["image"])
    assert c.stats["hits_disk"] == 1
    # Promotion re-evicted k1; corrupt its shard: loud journaled miss.
    k1_file = tmp_path / "rc" / "k1.rc"
    blob = bytearray(k1_file.read_bytes())
    blob[-1] ^= 0xFF
    k1_file.write_bytes(bytes(blob))
    assert c.get("k1") is None
    assert c.stats["corrupt_drops"] == 1
    assert ("dead", "k1") in journal
    assert not k1_file.exists()


def test_adoption_skips_dead_and_keeps_live(tmp_path):
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc")
    c.put("dead1", _arrays(0), {})
    c.put("live1", _arrays(1), {})       # spills dead1
    c.put("fill1", _arrays(2), {})       # spills live1
    # Restart over a recovered dead set: dead1's surviving file must be
    # unlinked at adoption, live1 adopted and served.
    c2 = ResultCache(disk_dir=tmp_path / "rc", dead=["dead1"])
    assert not (tmp_path / "rc" / "dead1.rc").exists()
    assert c2.get("dead1") is None
    assert c2.stats["dead_refusals"] == 1
    assert c2.get("live1") is not None
    assert sorted(c2.keys())[0] == "fill1" or "live1" in c2.keys()


def test_invalidate_all_and_len():
    c = ResultCache()
    c.put("a", _arrays(0), {})
    c.put("b", _arrays(1), {})
    assert len(c) == 2 and set(c.keys()) == {"a", "b"}
    c.invalidate_all()
    assert len(c) == 0
    assert c.get("a") is None and c.stats["dead_refusals"] >= 1


def test_disk_tier_promote_races_eviction_and_invalidation(tmp_path):
    """ISSUE 20 satellite: promote-on-hit racing eviction and
    invalidation on a one-slot memory tier.  Whatever interleaving the
    scheduler picks, a hit must serve the key's OWN bytes (anything
    else is a stale/torn serve) and no thread may see an exception
    escape the cache."""
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc")
    keys = [f"k{i}" for i in range(4)]
    want = {k: _arrays(i, n=256) for i, k in enumerate(keys)}
    for k in keys:
        c.put(k, want[k], {"who": k})
    errs: list[str] = []
    stop = threading.Event()

    def hammer(tid):
        rng = np.random.default_rng(tid)
        try:
            for step in range(250):
                k = keys[int(rng.integers(len(keys)))]
                roll = int(rng.integers(10))
                if roll < 6:
                    got = c.get(k)        # may promote from disk
                    if got is not None and not np.array_equal(
                            got[0]["image"], want[k]["image"]):
                        errs.append(f"{k}: foreign bytes served")
                elif roll < 9:
                    c.put(k, want[k], {"who": k})
                else:
                    c.invalidate(k)
        except Exception as e:  # noqa: BLE001 — the gate IS "no escape"
            errs.append(f"t{tid}: {type(e).__name__}: {e}")
        finally:
            stop.set()

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errs == []
    # The ladder stayed healthy: churn is evictions/promotes, never
    # corruption.
    assert c.stats["corrupt_drops"] == 0
    assert c.stats["spill_failures"] == 0
    # Post-race, a re-store of every key serves its own bytes again.
    for k in keys:
        c.put(k, want[k], {"who": k})
        got = c.get(k)
        assert got is not None
        np.testing.assert_array_equal(got[0]["image"],
                                      want[k]["image"])


def test_crash_between_spill_write_and_journal_is_refused(tmp_path):
    """The torn-publish crash window: a spill's bytes land at the final
    path but the process dies before the death record journals.  On
    restart adoption sees an un-tombstoned file whose CRC must refuse
    service — a torn write never becomes served bytes."""
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc")
    a0 = _arrays(0)
    c.put("k0", a0, {"who": "k0"})
    c.put("k1", _arrays(1), {})          # spills k0 to disk, valid
    blob = (tmp_path / "rc" / "k0.rc").read_bytes()
    # Simulated crash: rewrite the published file as its torn prefix
    # (what guarded_write's power-loss shape leaves), journal lost.
    (tmp_path / "rc" / "k0.rc").write_bytes(blob[:len(blob) // 2])
    c2 = ResultCache(disk_dir=tmp_path / "rc")
    assert c2.get("k0") is None
    assert c2.stats["corrupt_drops"] == 1
    assert not (tmp_path / "rc" / "k0.rc").exists()   # dropped loudly


def test_injected_torn_spill_kills_entry_and_cleans_path(tmp_path):
    """The same window driven through the fault site: a torn spill is
    swallowed (put never raises), the entry leaves the cache dead, and
    the half-written bytes do NOT await adoption at the final path."""
    journal = []
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc",
                    journal=lambda op, k: journal.append((op, k)))
    diskio.install_modes({"cache_spill": "torn_write"})
    with faults.injected("cache_spill:1"):
        c.put("k0", _arrays(0), {})
        c.put("k1", _arrays(1), {})      # evicts k0 -> torn spill
    assert c.stats["spill_failures"] == 1
    assert ("dead", "k0") in journal
    assert c.get("k0") is None
    assert list((tmp_path / "rc").glob("*.rc")) == []
    # Restart over the directory: nothing to adopt, nothing resurrects.
    c2 = ResultCache(disk_dir=tmp_path / "rc")
    assert c2.get("k0") is None and c2.stats["corrupt_drops"] == 0


def test_spill_failure_streak_demotes_reprobes_and_restores(tmp_path):
    """The disk-tier degrade ladder end to end on a fake clock:
    ``demote_after`` consecutive spill failures take the tier
    memory-only (journaled), the closed re-probe window drops spills
    without touching the device, one probe per window retries, and the
    first success journals the restore and re-arms."""
    clk = [0.0]
    journal = []
    c = ResultCache(capacity_entries=1, disk_dir=tmp_path / "rc",
                    demote_after=2, reprobe_s=5.0,
                    clock=lambda: clk[0],
                    journal=lambda op, k: journal.append((op, k)))
    diskio.install_modes({"cache_spill": "eio"})
    with faults.injected("cache_spill:*"):
        for i in range(3):               # two failures demote; the
            c.put(f"k{i}", _arrays(i), {})   # third never probes
    assert c.stats["spill_failures"] == 2
    assert c.stats["tier_demotions"] == 1
    assert ("tier_demoted", "disk") in journal
    assert c.stats["reprobes"] == 0      # window closed: no IO attempt
    # Window opens but the device is still dying: probe fails, window
    # re-closes.
    clk[0] = 6.0
    with faults.injected("cache_spill:*"):
        c.put("k3", _arrays(3), {})
    assert c.stats["reprobes"] == 1
    assert c.stats["spill_failures"] == 3
    assert c.stats["tier_demotions"] == 1          # already demoted
    # Healed device, open window: the probe spill succeeds and the
    # tier is journaled back.
    diskio.uninstall_modes()
    clk[0] = 12.0
    c.put("k4", _arrays(4), {})
    assert c.stats["tier_restores"] == 1
    assert ("tier_restored", "disk") in journal
    assert c.stats["spills"] == 1
    # Fully healed: the next eviction spills without a probe window.
    c.put("k5", _arrays(5), {})
    assert c.stats["spills"] == 2
    got = c.get("k4")                    # disk hit after the restore
    assert got is not None
    np.testing.assert_array_equal(got[0]["image"], _arrays(4)["image"])


# ------------------------------------------------------------- WAL


def test_wal_state_folds_cache_records_and_round_trips():
    st = wal_mod.WALState()
    st.apply({"kind": "cache", "op": "dead", "ckey": "k1"})
    st.apply({"kind": "cache", "op": "dead", "ckey": "k2"})
    assert set(st.cache_dead) == {"k1", "k2"}
    st.apply({"kind": "cache", "op": "live", "ckey": "k1"})
    assert set(st.cache_dead) == {"k2"}
    st2 = wal_mod.WALState()
    st2.load_wire(st.to_wire())
    assert set(st2.cache_dead) == {"k2"}


def test_router_wal_replay_recovers_cache_dead(tmp_path):
    w = wal_mod.RouterWAL(tmp_path / "ctl.wal", fsync=False)
    w.append("cache", op="dead", ckey="gone")
    w.append("cache", op="dead", ckey="back")
    w.append("cache", op="live", ckey="back")
    w.close()
    w2 = wal_mod.RouterWAL(tmp_path / "ctl.wal", fsync=False)
    assert set(w2.state.cache_dead) == {"gone"}
    # The rebuilt cache refuses the recovered-dead key outright.
    c = ResultCache(dead=w2.state.cache_dead)
    assert c.get("gone") is None and c.stats["dead_refusals"] == 1
    w2.close()


# ------------------------------------------------- service integration


def test_service_cache_default_off():
    svc = ConvolutionService(_mesh(), max_delay_s=0.002)
    try:
        r = svc.submit(Request(image=_img(), iters=1, request_id="a"),
                       timeout=120)
        assert r.ok and r.cache == "off"
        assert svc.snapshot()["cache"] is None
    finally:
        svc.close()


def test_service_duplicate_hits_flat_engine_and_oracle_bytes():
    svc = ConvolutionService(_mesh(), max_delay_s=0.002,
                             cache=ResultCache())
    img = _img(seed=9)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    try:
        r0 = svc.submit(Request(image=img, iters=2, request_id="c0"),
                        timeout=120)
        assert r0.ok and r0.cache == "miss" and len(r0.digest) == 64
        np.testing.assert_array_equal(r0.image, want)
        eng = dict(svc.engine.stats)
        for i in range(3):
            r = svc.submit(Request(image=img, iters=2,
                                   request_id=f"c{i + 1}"), timeout=120)
            assert r.ok and r.cache == "hit" and r.digest == r0.digest
            assert r.batch_size == 1
            np.testing.assert_array_equal(r.image, want)
        for k in ("compiles", "batches", "images"):
            assert svc.engine.stats[k] == eng[k], k
        assert svc.stats["cache_hits"] == 3
        # A mutated hit copy must not poison the shared cached entry.
        r.image[0, 0] ^= 1
        r2 = svc.submit(Request(image=img, iters=2, request_id="c9"),
                        timeout=120)
        np.testing.assert_array_equal(r2.image, want)
        # Different iters = different result key = real execution.
        r3 = svc.submit(Request(image=img, iters=3, request_id="c10"),
                        timeout=120)
        assert r3.ok and r3.cache == "miss"
    finally:
        svc.close()


def test_service_converge_final_cached_single_row_stream():
    svc = ConvolutionService(_mesh(), max_delay_s=0.002,
                             cache=ResultCache())
    img = _img(seed=11)

    def run(rid):
        req = Request(image=img, iters=10, request_id=rid,
                      quantize=False)
        return list(svc.submit_progressive(req, tol=5.0, max_iters=200))

    try:
        rows1 = run("cv0")
        assert rows1 and rows1[-1].final and rows1[-1].converged
        assert rows1[-1].cache == "miss"
        rows2 = run("cv1")
        assert len(rows2) == 1
        assert rows2[0].final and rows2[0].converged
        assert rows2[0].cache == "hit"
        np.testing.assert_array_equal(rows2[0].image, rows1[-1].image)
        assert rows2[0].iters == rows1[-1].iters
    finally:
        svc.close()


def test_reshape_invalidates_cache():
    svc = ConvolutionService(_mesh((1, 2)), max_delay_s=0.002,
                             cache=ResultCache())
    img = _img(seed=13)
    try:
        svc.submit(Request(image=img, iters=1, request_id="r0"),
                   timeout=120)
        assert len(svc.cache) == 1
        svc.reshape("2x2")
        assert len(svc.cache) == 0
        r = svc.submit(Request(image=img, iters=1, request_id="r1"),
                       timeout=120)
        assert r.ok and r.cache == "miss"   # stale-grid meta never served
    finally:
        svc.close()


# ------------------------------------------------- shared-evidence IO


def test_rewrite_shared_jsonl_unlaned_owner_preserves_lanes(tmp_path):
    p = tmp_path / "curve.jsonl"
    p.write_text(json.dumps({"lane": "other", "x": 1}) + "\n"
                 + json.dumps({"old": True}) + "\n"
                 + "not json\n")
    kept = rewrite_shared_jsonl(p, [{"mine": 1}, {"mine": 2}], lane=None)
    assert kept == 1
    rows = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert rows[0] == {"mine": 1} and rows[1] == {"mine": 2}
    assert rows[2] == {"lane": "other", "x": 1}
    assert len(rows) == 3                    # old un-laned + torn dropped


def test_rewrite_shared_jsonl_lane_owner_stamps_and_replaces(tmp_path):
    p = tmp_path / "curve.jsonl"
    rewrite_shared_jsonl(p, [{"a": 1}], lane=None)
    rewrite_shared_jsonl(p, [{"b": 1}], lane="cache_skew")
    rewrite_shared_jsonl(p, [{"c": 1}], lane="router_scale")
    # Each lane owner replaces only its own rows.
    rewrite_shared_jsonl(p, [{"b": 2}], lane="cache_skew")
    rows = [json.loads(ln) for ln in p.read_text().splitlines()]
    lanes = [r.get("lane") for r in rows]
    assert lanes.count("cache_skew") == 1
    assert {"lane": "cache_skew", "b": 2} in rows
    assert {"lane": "router_scale", "c": 1} in rows
    assert {"a": 1} in rows


def _load_static_check():
    spec = importlib.util.spec_from_file_location(
        "static_check", SCRIPTS / "static_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_static_gate_catches_direct_shared_curve_write(tmp_path):
    sc = _load_static_check()
    bad = tmp_path / "bad_smoke.py"
    bad.write_text(
        "from pathlib import Path\n"
        "curve_path = Path('evidence/scale_curve.jsonl')\n"
        "with open(curve_path, 'w') as f:\n"
        "    f.write('{}')\n")
    probs = sc.check_shared_curve_writes([bad])
    assert len(probs) == 1 and "evidence_io" in probs[0]
    # write_text and Path.open('w') are writes too.
    bad.write_text("from pathlib import Path\n"
                   "Path('x/scale_curve.jsonl').write_text('')\n")
    assert sc.check_shared_curve_writes([bad])
    bad.write_text("curve = open('evidence/scale_curve.jsonl')\n")
    assert not sc.check_shared_curve_writes([bad])   # read mode is fine
    # The helper module itself is the one sanctioned writer.
    helper = tmp_path / "evidence_io.py"
    helper.write_text("curve_path = 'scale_curve.jsonl'\n"
                      "f = open(curve_path, 'w')\n")
    assert not sc.check_shared_curve_writes([helper])


def test_repo_tree_passes_shared_curve_rule():
    sc = _load_static_check()
    assert sc.check_shared_curve_writes(sc.py_files()) == []


def test_static_gate_catches_unguarded_disk_write(tmp_path):
    sc = _load_static_check()
    serving = tmp_path / "serving"
    serving.mkdir()
    bad = serving / "new_subsystem.py"
    bad.write_text("with open('ledger.json', 'w') as f:\n"
                   "    f.write('{}')\n")
    probs = sc.check_guarded_disk_writes([bad])
    assert len(probs) == 1 and "diskio" in probs[0]
    # os.replace, os.fdopen('w'), Path.open('w'), write_text: writes too.
    bad.write_text("import os\nos.replace('a', 'b')\n")
    assert sc.check_guarded_disk_writes([bad])
    bad.write_text("import os\nf = os.fdopen(3, 'wb')\n")
    assert sc.check_guarded_disk_writes([bad])
    bad.write_text("from pathlib import Path\n"
                   "Path('x').open('a').write('')\n")
    assert sc.check_guarded_disk_writes([bad])
    bad.write_text("from pathlib import Path\n"
                   "Path('x').write_text('')\n")
    assert sc.check_guarded_disk_writes([bad])
    # Read-mode opens and str.replace are not writes.
    bad.write_text("open('ledger.json').read()\n"
                   "s = 'a-b'.replace('-', '_')\n")
    assert not sc.check_guarded_disk_writes([bad])
    # A pragma on the call line exempts it (with a stated reason).
    bad.write_text("f = open('x', 'w')  # diskio: exempt — snapshot\n")
    assert not sc.check_guarded_disk_writes([bad])
    # Guarded-owner modules write directly (they consult diskio inside).
    owner = serving / "wal.py"
    owner.write_text("f = open('wal.jsonl', 'a')\n")
    assert not sc.check_guarded_disk_writes([owner])
    # Out-of-scope dirs are not this check's business.
    other_dir = tmp_path / "parallel"
    other_dir.mkdir()
    other = other_dir / "tool.py"
    other.write_text("f = open('x', 'w')\n")
    assert not sc.check_guarded_disk_writes([other])


def test_repo_tree_passes_guarded_disk_write_rule():
    sc = _load_static_check()
    assert sc.check_guarded_disk_writes(sc.py_files()) == []
