"""Periodic (torus) boundary mode: ring-topology halo exchange."""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.models import ConvolutionModel, JacobiSolver
from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


@pytest.mark.parametrize("mshape", [(1, 1), (2, 2), (2, 4), (4, 1)])
def test_periodic_bitexact_vs_wrap_oracle(mshape):
    # 32x48 divides by all grids; wrap-around ghosts must match np.pad(wrap).
    img = imageio.generate_test_image(32, 48, "grey", seed=51)
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(img, filt, 5, boundary="periodic")
    x = img[None].astype(np.float32)
    out = step.sharded_iterate(x, filt, 5, mesh=_mesh(mshape),
                               boundary="periodic")
    got = np.asarray(out)[0].astype(np.uint8)
    np.testing.assert_array_equal(got, want)


def test_periodic_corner_wrap():
    # A single bright pixel at the corner must bleed to all three other
    # corners under periodic blur (the diagonal torus wrap).
    img = np.zeros((8, 8), np.uint8)
    img[0, 0] = 255
    filt = filters.get_filter("blur3")
    want = oracle.convolve_once_u8(img, filt, boundary="periodic")
    assert want[7, 7] > 0  # diagonal wrap in the oracle itself
    x = img[None].astype(np.float32)
    out = step.sharded_iterate(x, filt, 1, mesh=_mesh((2, 2)),
                               boundary="periodic")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_periodic_fused_and_pallas(rgb_small):
    # 24x36 divides by 2x2; fuse + pallas + periodic composition.
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(rgb_small, filt, 6, boundary="periodic")
    x = imageio.interleaved_to_planar(rgb_small).astype(np.float32)
    for kw in ({"fuse": 3}, {"backend": "pallas"},
               {"backend": "pallas", "fuse": 2, "storage": "bf16"}):
        out = step.sharded_iterate(x, filt, 6, mesh=_mesh((2, 2)),
                                   boundary="periodic", **kw)
        got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
        np.testing.assert_array_equal(got, want, err_msg=str(kw))


def test_periodic_requires_divisible():
    img = np.zeros((1, 33, 48), np.float32)  # 33 not divisible by 2
    with pytest.raises(ValueError, match="divisible"):
        step.sharded_iterate(img, filters.get_filter("blur3"), 1,
                             mesh=_mesh((2, 2)), boundary="periodic")


def test_periodic_jacobi_mass_conservation():
    # A periodic averaging stencil conserves total mass exactly in the
    # dyadic regime — a physics sanity check the zero boundary would fail.
    filt = filters.get_filter("jacobi3")
    img = imageio.generate_test_image(16, 32, "grey", seed=52)
    x = img[None].astype(np.float32)
    out = step.sharded_iterate(x, filt, 10, mesh=_mesh((2, 2)),
                               quantize=False, boundary="periodic")
    np.testing.assert_allclose(float(np.asarray(out).sum()),
                               float(x.sum()), rtol=1e-6)


def test_periodic_solver_api():
    # blur3 (damped averaging: no unit-magnitude checkerboard mode, unlike
    # the pure 4-point jacobi stencil) converges to the uniform mean field.
    s = JacobiSolver(filt="blur3", tol=1e-4, max_iters=2000, check_every=20,
                     mesh=_mesh((2, 2)), boundary="periodic")
    x = imageio.generate_test_image(16, 16, "grey", seed=53)[None].astype(
        np.float32)
    out, iters = s.solve(x)
    assert iters < 2000
    np.testing.assert_allclose(out, np.full_like(out, x.mean()), atol=0.05)
