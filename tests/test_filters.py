import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters


def test_registry_contents():
    for name in ["blur3", "box3", "gaussian5", "edge3", "edge5", "sharpen3",
                 "identity3", "jacobi3"]:
        f = filters.get_filter(name)
        assert f.name == name
        assert f.taps.dtype == np.float32
        assert f.size in (3, 5)
        assert f.radius == f.size // 2


def test_blur3_is_reference_kernel():
    f = filters.get_filter("blur3")
    expected = np.array([[1, 2, 1], [2, 4, 2], [1, 2, 1]], np.float32) / 16.0
    np.testing.assert_array_equal(f.taps, expected)
    assert abs(float(f.taps.sum()) - 1.0) < 1e-7


def test_normalized_filters_sum_to_one():
    for name in ["blur3", "box3", "gaussian5", "jacobi3"]:
        assert abs(float(filters.get_filter(name).taps.sum()) - 1.0) < 1e-6


def test_unknown_filter_raises():
    with pytest.raises(KeyError, match="unknown filter"):
        filters.get_filter("nope")


def test_even_size_rejected():
    with pytest.raises(ValueError):
        filters.make_filter("bad", np.ones((4, 4)))


def test_gaussian_builder():
    g = filters.gaussian(7, 1.5)
    assert g.size == 7 and g.radius == 3
    assert abs(float(g.taps.sum()) - 1.0) < 1e-6
    # symmetric
    np.testing.assert_allclose(g.taps, g.taps[::-1, ::-1])


def test_custom_filter_any_odd_size():
    f = filters.make_filter("box7", np.ones((7, 7)), divisor=49)
    assert f.size == 7
    assert abs(float(f.taps.sum()) - 1.0) < 1e-6


def test_convex_truth_table():
    # Convex = non-negative taps summing to <= 1: the quantize-mode clip is
    # provably the identity and the Pallas kernels elide it (~2 VPU ops/px
    # per level).  Filters with negative taps or gain > 1 must keep it.
    for name in ["blur3", "box3", "gaussian5", "jacobi3", "identity3"]:
        assert filters.get_filter(name).convex, name
    for name in ["edge3", "edge5", "sharpen3"]:
        assert not filters.get_filter(name).convex, name
    assert filters.gaussian(7, 1.5).convex
