"""Sharded file→device→file path: blocks only, bit-exact vs the oracle."""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.models import ConvolutionModel
from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.utils import imageio, sharded_io


def _mesh(shape):
    n = shape[0] * shape[1]
    return mesh_lib.make_grid_mesh(jax.devices()[:n], shape)


@pytest.mark.parametrize("mode", ["grey", "rgb"])
def test_load_sharded_layout(tmp_path, mode):
    img = imageio.generate_test_image(37, 53, mode, seed=31)
    p = str(tmp_path / "in.raw")
    imageio.write_raw(p, img)
    m = _mesh((2, 4))
    arr = sharded_io.load_sharded(p, 37, 53, mode, m)
    C = 3 if mode == "rgb" else 1
    # padded to block multiples of the 2x4 grid
    assert arr.shape == (C, 38, 56)
    # valid region matches, pad rim is zero
    host = np.asarray(arr)
    np.testing.assert_array_equal(
        host[:, :37, :53], imageio.interleaved_to_planar(img).astype(np.float32)
    )
    assert (host[:, 37:, :] == 0).all() and (host[:, :, 53:] == 0).all()


@pytest.mark.parametrize("mode", ["grey", "rgb"])
def test_save_sharded_roundtrip(tmp_path, mode):
    img = imageio.generate_test_image(29, 43, mode, seed=32)
    src, dst = str(tmp_path / "a.raw"), str(tmp_path / "b.raw")
    imageio.write_raw(src, img)
    m = _mesh((4, 2))
    arr = sharded_io.load_sharded(src, 29, 43, mode, m)
    sharded_io.save_sharded(dst, arr, 29, 43, mode)
    np.testing.assert_array_equal(imageio.read_raw(dst, 29, 43, mode), img)


def test_run_raw_file_sharded_end_to_end(tmp_path):
    img = imageio.generate_test_image(45, 61, "rgb", seed=33)
    src, dst = str(tmp_path / "in.raw"), str(tmp_path / "out.raw")
    imageio.write_raw(src, img)
    model = ConvolutionModel(filt="blur3", mesh=_mesh((2, 4)))
    model.run_raw_file_sharded(src, dst, 45, 61, "rgb", 5)
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 5)
    np.testing.assert_array_equal(imageio.read_raw(dst, 45, 61, "rgb"), want)


def test_northstar_rehearsal_small():
    """The north-star rehearsal pipeline (scripts/northstar_rehearsal.py)
    at a fast size: stripe-written input, sharded-IO + checkpoint child,
    naive-pipeline child for the differential RSS proof, windowed oracle
    spot-check, byte-identical outputs.  The recorded 8192² rehearsal
    row lives in evidence/; this keeps the machinery itself under test.
    """
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "northstar_rehearsal.py")
    env = dict(os.environ, NS_ROWS="192", NS_COLS="256")
    proc = subprocess.run([sys.executable, script], env=env,
                          capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    import json

    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["outputs_identical"]
    assert all(row["oracle_windows_bitexact"].values())
