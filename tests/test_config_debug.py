import numpy as np
import pytest

from parallel_convolution_tpu import cli
from parallel_convolution_tpu.ops import filters
from parallel_convolution_tpu.utils import debug
from parallel_convolution_tpu.utils.config import RunConfig


def test_config_roundtrip():
    c = RunConfig(rows=100, cols=200, mode="rgb", backend="pallas",
                  mesh_shape=(2, 4), fuse=4, storage="bf16",
                  tile=(1024, 512))
    c2 = RunConfig.from_json(c.to_json())
    assert c2 == c
    assert c2.tile == (1024, 512)  # JSON list normalizes back to a tuple


def test_config_validation():
    with pytest.raises(ValueError, match="grey"):
        RunConfig(rows=1, cols=1, mode="cmyk")
    with pytest.raises(ValueError, match="backend"):
        RunConfig(rows=1, cols=1, backend="cuda")
    with pytest.raises(ValueError, match="positive"):
        RunConfig(rows=0, cols=1)
    with pytest.raises(ValueError, match="tile"):
        RunConfig(rows=1, cols=1, tile=(0, 128))
    with pytest.raises(ValueError, match="tile"):
        RunConfig(rows=1, cols=1, tile=(8, 128, 2))


def test_config_build_model(grey_small):
    from parallel_convolution_tpu.ops import oracle

    c = RunConfig(rows=24, cols=36, filter_name="blur3", mesh_shape=(2, 2))
    model = c.build_model()
    got = model.run_image(grey_small, 3)
    want = oracle.run_serial_u8(grey_small, filters.get_filter("blur3"), 3)
    np.testing.assert_array_equal(got, want)


def test_checked_correlate_clean(grey_small):
    x = grey_small[None].astype(np.float32)
    out = debug.checked_correlate(x, filters.get_filter("blur3"))
    assert np.isfinite(np.asarray(out)).all()


def test_checked_correlate_catches_nan():
    from jax.experimental import checkify

    x = np.ones((1, 8, 8), np.float32)
    x[0, 3, 3] = np.nan
    with pytest.raises(checkify.JaxRuntimeError, match="non-finite"):
        debug.checked_correlate(x, filters.get_filter("blur3"))


def test_assert_u8_range():
    debug.assert_u8_range(np.array([0.0, 255.0, 17.0]))
    with pytest.raises(AssertionError, match="invariant"):
        debug.assert_u8_range(np.array([0.0, 256.0]))
    with pytest.raises(AssertionError):
        debug.assert_u8_range(np.array([1.5]))


def test_find_nonfinite():
    a = np.zeros((4, 4))
    a[1, 2] = np.inf
    assert debug.find_nonfinite(a) == [(1, 2)]


def test_cli_convert_pgm_ppm(tmp_path):
    src = str(tmp_path / "in.raw")
    cli.main(["generate", src, "10", "12", "grey"])
    out = str(tmp_path / "img.pgm")
    assert cli.main(["convert", src, "10", "12", "grey", "-o", out]) == 0
    data = open(out, "rb").read()
    assert data.startswith(b"P5\n12 10\n255\n") and len(data) == 13 + 120

    src2 = str(tmp_path / "in2.raw")
    cli.main(["generate", src2, "10", "12", "rgb"])
    out2 = str(tmp_path / "img.ppm")
    assert cli.main(["convert", src2, "10", "12", "rgb", "-o", out2]) == 0
    assert open(out2, "rb").read().startswith(b"P6\n12 10\n255\n")
