"""Separable fast path + batch (DP) API."""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.models import ConvolutionModel
from parallel_convolution_tpu.ops import conv, filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def test_separable_factors_dyadic():
    col, row = filters.get_filter("blur3").separable()
    np.testing.assert_array_equal(col * 4, [1, 2, 1])
    np.testing.assert_array_equal(row * 4, [1, 2, 1])
    col5, row5 = filters.get_filter("gaussian5").separable()
    np.testing.assert_array_equal(col5 * 16, [1, 4, 6, 4, 1])
    np.testing.assert_array_equal(row5 * 16, [1, 4, 6, 4, 1])
    assert filters.get_filter("edge3").separable() is None


@pytest.mark.parametrize("name", ["blur3", "gaussian5"])
def test_separable_backend_bitexact(grey_odd, name):
    filt = filters.get_filter(name)
    want = oracle.run_serial_u8(grey_odd, filt, 5)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 5, mesh=_mesh((2, 4)),
                               backend="separable")
    got = np.asarray(out)[0].astype(np.uint8)
    np.testing.assert_array_equal(got, want)


def test_separable_fallback_nonseparable(grey_small):
    # edge3 has no factorization: backend must silently use the 2D path.
    filt = filters.get_filter("edge3")
    want = oracle.run_serial_u8(grey_small, filt, 3)
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               backend="separable")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_separable_with_fusion_bf16(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 8)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 8, mesh=_mesh((2, 2)),
                               backend="separable", fuse=4, storage="bf16")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


@pytest.mark.parametrize("name", ["blur3", "gaussian5"])
def test_pallas_sep_backend_bitexact(grey_odd, name):
    filt = filters.get_filter(name)
    want = oracle.run_serial_u8(grey_odd, filt, 5)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 5, mesh=_mesh((2, 2)),
                               backend="pallas_sep")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_pallas_sep_fallback_nonseparable(grey_small):
    filt = filters.get_filter("edge3")
    want = oracle.run_serial_u8(grey_small, filt, 3)
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               backend="pallas_sep")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_pallas_sep_fused_bf16(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 8)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 8, mesh=_mesh((2, 2)),
                               backend="pallas_sep", fuse=4, storage="bf16")
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_batch_api_matches_individual():
    model = ConvolutionModel(filt="blur3", mesh=_mesh((2, 2)))
    imgs = [imageio.generate_test_image(21, 33, "grey", seed=s)
            for s in (1, 2)]
    imgs.append(imageio.generate_test_image(21, 33, "rgb", seed=3))
    batch = model.run_images(imgs, 4)
    assert len(batch) == 3
    for im, got in zip(imgs, batch):
        want = oracle.run_serial_u8(im, filters.get_filter("blur3"), 4)
        np.testing.assert_array_equal(got, want)
        assert got.shape == im.shape
