"""Binary wire format + continuous batching (round 20 acceptance).

The tentpole properties, all on the CPU mesh:

* frame codec round-trip for every registered dtype, zero-length and
  0-d arrays included; every malformation — truncation anywhere, CRC
  corruption (via the chaos helper the drills use), trailing garbage —
  is the TYPED :class:`frames.BadFrame`, surfacing as the typed
  ``bad_frame`` 400 at the frontend, never a handler crash;
* the two codec arms are byte-identical end to end on BOTH endpoints
  (``/v1/convolve`` one-shot, ``/v1/converge`` streamed), in-process
  and over loopback HTTP — the binary wire is an encoding, never a
  different answer;
* near-miss shapes co-batch through the shape-bucketed lanes (padded to
  the bucket, cropped on the way out) byte-identically to their
  individual runs, with the pad-waste ratio exported;
* the batcher refills mid-flight: under sustained load the pipelined
  batcher overlaps collection with execution (``refills > 0``) while
  the ``pipeline_depth=0`` drain arm structurally cannot, and
  ``max_observed_depth`` counts in-flight items, not just queued ones.
"""

from __future__ import annotations

import base64
import json
import threading
import time

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.resilience import degrade, faults
from parallel_convolution_tpu.resilience.retry import RetryPolicy
from parallel_convolution_tpu.serving import chaos, frames
from parallel_convolution_tpu.serving.batcher import MicroBatcher
from parallel_convolution_tpu.serving.frontend import (
    InProcessClient, iter_framed_rows, make_http_server,
)
from parallel_convolution_tpu.serving.service import ConvolutionService
from parallel_convolution_tpu.utils import imageio


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


def _mesh(shape=(2, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _service(**kw):
    kw.setdefault("mesh", _mesh())
    kw.setdefault("max_delay_s", 0.02)
    kw.setdefault("retry_policy",
                  RetryPolicy(max_attempts=3, base_delay=0.01,
                              max_delay=0.05))
    return ConvolutionService(kw.pop("mesh"), **kw)


def _img(h=24, w=36, mode="grey", seed=1):
    return imageio.generate_test_image(h, w, mode, seed=seed)


def _b64(img) -> str:
    return base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii")


def _base_body(img, **kw):
    body = {"rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
            "filter": "blur3", "iters": 1, "backend": "shifted",
            "storage": "f32", "fuse": 1, "boundary": "zero"}
    body.update(kw)
    return body


# ------------------------------------------------------------ frame codec

def test_frame_roundtrip_every_dtype():
    rng = np.random.default_rng(0)
    for code, dt in frames.DTYPE_CODES.items():
        arr = (rng.random((3, 5)) * 100).astype(dt)
        buf = frames.encode_frame(arr)
        got, end = frames.decode_frame(buf)
        assert end == len(buf)
        assert got.dtype == dt and got.shape == arr.shape
        assert got.tobytes() == arr.tobytes(), f"dtype code {code}"
        # Zero-copy contract: the decode is a read-only VIEW.
        assert not got.flags["WRITEABLE"]


def test_frame_roundtrip_zero_length_and_zero_dim():
    empty = np.zeros((0,), np.float32)
    got, _ = frames.decode_frame(frames.encode_frame(empty))
    assert got.shape == (0,) and got.dtype == np.float32
    scalar = np.float32(3.25)
    got, _ = frames.decode_frame(frames.encode_frame(scalar))
    assert got.shape == () and float(got) == 3.25


def test_envelope_roundtrip_and_opaque_forward():
    img = _img(17, 23)
    state = np.linspace(0, 1, 12, dtype=np.float32).reshape(3, 4)
    header = {"request_id": "r1", "iters": 2, "tenant": "t0"}
    env = frames.encode_envelope(header, {"image": img, "state": state})
    back, arrays = frames.decode_envelope(env)
    assert back["request_id"] == "r1" and "_frame_fields" not in back
    assert arrays["image"].tobytes() == img.tobytes()
    assert arrays["state"].tobytes() == state.tobytes()
    # The router's path: header parsed, frames OPAQUE, restamped, and
    # re-joined — the tensors must survive the round untouched.
    head, raw = frames.split_envelope(env)
    head["router"] = {"replica": "r0"}
    back2, arrays2 = frames.decode_envelope(
        frames.join_envelope(head, raw))
    assert back2["router"] == {"replica": "r0"}
    assert arrays2["image"].tobytes() == img.tobytes()


def test_truncated_frame_is_typed_bad_frame():
    buf = frames.encode_frame(np.arange(64, dtype=np.uint8))
    for cut in (1, 4, 7, 10, len(buf) // 2, len(buf) - 1):
        with pytest.raises(frames.BadFrame):
            frames.decode_frame(buf[:cut])


def test_envelope_malformations_are_typed():
    img = _img(8, 8)
    env = frames.encode_envelope({"a": 1}, {"image": img})
    with pytest.raises(frames.BadFrame):
        frames.decode_envelope(env + b"trailing-garbage")
    with pytest.raises(frames.BadFrame):
        frames.decode_envelope(b"not an envelope at all")
    with pytest.raises(frames.BadFrame):
        frames.decode_envelope(env[: len(env) // 2])


def test_crc_corruption_detected_across_seed_sweep():
    # The chaos helper flips one payload bit near the END of the buffer
    # (inside the last frame's payload), so structural checks pass and
    # the CRC is what must catch it — swept so detection isn't
    # positional luck.
    img = _img(32, 32)
    env = frames.encode_envelope(_base_body(img), {"image": img})
    for seed in range(16):
        corrupted = chaos.corrupt_frame_bytes(env, seed=seed)
        assert corrupted != env
        with pytest.raises(frames.BadFrame):
            frames.decode_envelope(corrupted)


# --------------------------------------------------- typed 400 at the door

def test_bad_frame_is_typed_400_not_a_crash():
    svc = _service()
    try:
        client = InProcessClient(svc)
        img = _img()
        env = frames.encode_envelope(
            _base_body(img, request_id="bf1"), {"image": img})
        for raw in (b"garbage", chaos.corrupt_frame_bytes(env, seed=3)):
            status, data = client.request_frames(raw, timeout=30.0)
            assert status == 400
            header, arrays = frames.decode_envelope(data)
            assert header["ok"] is False
            assert header["rejected"] == "bad_frame"
            assert not arrays
        # The service survives to serve the next (valid) request.
        status, data = client.request_frames(env, timeout=60.0)
        assert status == 200
        header, _ = frames.decode_envelope(data)
        assert header["ok"]
    finally:
        svc.close()


# ------------------------------------------------- byte-identity, in-proc

def test_convolve_json_vs_frames_byte_identical():
    svc = _service()
    try:
        client = InProcessClient(svc)
        img = _img(40, 52)
        js, jresp = client.request(
            dict(_base_body(img, iters=2), image_b64=_b64(img),
                 request_id="j1"), timeout=60.0)
        fs, raw = client.request_frames(
            frames.encode_envelope(
                _base_body(img, iters=2, request_id="f1"),
                {"image": img}), timeout=60.0)
        assert js == fs == 200
        fheader, farrays = frames.decode_envelope(raw)
        assert jresp["ok"] and fheader["ok"]
        assert jresp["wire"] == "json" and fheader["wire"] == "frames"
        assert (base64.b64decode(jresp["image_b64"])
                == farrays["image"].tobytes())
        want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
        assert farrays["image"].tobytes() == want.tobytes()
    finally:
        svc.close()


def test_converge_stream_json_vs_frames_identical():
    svc = _service()
    try:
        client = InProcessClient(svc)
        img = _img(32, 40, seed=5)
        base = {"rows": 32, "cols": 40, "mode": "grey", "filter": "blur3",
                "backend": "shifted", "storage": "f32", "fuse": 1,
                "boundary": "zero", "tol": 5e-3, "max_iters": 40,
                "check_every": 10, "quantize": False, "solver": "jacobi"}
        js, jrows = client.converge(
            dict(base, image_b64=_b64(img), request_id="cj1"),
            timeout=60.0)
        jrows = list(jrows)
        fs, frows = client.converge_frames(
            frames.encode_envelope(dict(base, request_id="cf1"),
                                   {"image": img}), timeout=60.0)
        frows = [frames.decode_envelope(r) for r in frows]
        assert js == fs == 200
        assert len(jrows) == len(frows) >= 2
        for jr, (fh, fa) in zip(jrows, frows):
            assert jr["kind"] == fh["kind"]
            assert jr.get("iteration") == fh.get("iteration")
            assert jr["wire"] == "json" and fh["wire"] == "frames"
            assert (base64.b64decode(jr["image_b64"])
                    == fa["image"].tobytes())
        assert jrows[-1]["kind"] == frows[-1][0]["kind"] == "final"
    finally:
        svc.close()


# ------------------------------------------------- byte-identity, HTTP

def test_http_frames_roundtrip_and_framed_stream():
    import http.client
    import socket
    import urllib.request

    try:
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        probe.close()
    except OSError:
        pytest.skip("loopback sockets unavailable in this sandbox")
    svc = _service()
    server = make_http_server(svc, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        img = _img(36, 44, seed=7)
        jbody = dict(_base_body(img, iters=2), image_b64=_b64(img),
                     request_id="hj1")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/convolve",
            data=json.dumps(jbody).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            jresp = json.loads(resp.read())
        env = frames.encode_envelope(
            _base_body(img, iters=2, request_id="hf1"), {"image": img})
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/convolve", data=env,
            headers={"Content-Type": frames.FRAMES_CONTENT_TYPE})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers.get("Content-Type") \
                == frames.FRAMES_CONTENT_TYPE
            fheader, farrays = frames.decode_envelope(resp.read())
        assert jresp["ok"] and fheader["ok"]
        assert (base64.b64decode(jresp["image_b64"])
                == farrays["image"].tobytes())

        # Framed converge: length-prefixed rows, flushed per row.
        cbase = {"rows": 36, "cols": 44, "mode": "grey",
                 "filter": "blur3", "backend": "shifted",
                 "storage": "f32", "fuse": 1, "boundary": "zero",
                 "tol": 5e-3, "max_iters": 30, "check_every": 10,
                 "quantize": False, "solver": "jacobi"}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/converge",
            data=json.dumps(dict(cbase, image_b64=_b64(img),
                                 request_id="hcj1")).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            jrows = [json.loads(line) for line in resp if line.strip()]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/converge",
            data=frames.encode_envelope(dict(cbase, request_id="hcf1"),
                                        {"image": img}),
            headers={"Content-Type": frames.FRAMES_CONTENT_TYPE})
        conn_rows = []
        with urllib.request.urlopen(req, timeout=120) as resp:
            for raw in iter_framed_rows(resp):
                conn_rows.append(frames.decode_envelope(raw))
        assert len(jrows) == len(conn_rows) >= 2
        for jr, (fh, fa) in zip(jrows, conn_rows):
            assert jr["kind"] == fh["kind"]
            assert (base64.b64decode(jr["image_b64"])
                    == fa["image"].tobytes())

        # A malformed framed POST is a typed 400 (framed envelope back).
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            conn.request("POST", "/v1/convolve", body=b"garbage",
                         headers={"Content-Type":
                                  frames.FRAMES_CONTENT_TYPE})
            resp = conn.getresponse()
            assert resp.status == 400
            header, _ = frames.decode_envelope(resp.read())
            assert header["rejected"] == "bad_frame"
        finally:
            conn.close()
    finally:
        server.shutdown()
        server.server_close()
        svc.close()


# ------------------------------------------- shape-bucketed co-batching

def test_near_miss_shapes_cobatch_byte_identical():
    # Three thumbnails in ONE 128x128 bucket (iters=1, zero boundary:
    # the pad-to-bucket eligibility window) submitted together: they
    # must co-batch (pad waste visible) and every result must equal its
    # own serial oracle — padding is an execution detail, never an
    # answer change.
    svc = _service(max_delay_s=0.05, max_batch=4)
    try:
        client = InProcessClient(svc)
        shapes = [(100, 120), (97, 126), (110, 100)]
        imgs = [_img(h, w, seed=9 + i) for i, (h, w) in enumerate(shapes)]
        results: dict[int, dict] = {}

        def one(i):
            status, resp = client.request(
                dict(_base_body(imgs[i]), image_b64=_b64(imgs[i]),
                     request_id=f"nm{i}"), timeout=60.0)
            results[i] = {"status": status, **resp}

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(imgs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        for i, img in enumerate(imgs):
            assert results[i]["status"] == 200 and results[i]["ok"]
            want = oracle.run_serial_u8(
                img, filters.get_filter("blur3"), 1)
            assert (base64.b64decode(results[i]["image_b64"])
                    == want.tobytes()), f"shape {shapes[i]}"
        # Co-batching happened: fewer flushes than images, and the
        # padded-pixel waste is exported.
        assert svc.batcher.stats["flushes"] < len(imgs)
        assert svc.batcher.stats["pad_waste_ratio"] > 0.0
    finally:
        svc.close()


# --------------------------------------------------- continuous batching

def _sleepy_batcher(pipeline_depth, **kw):
    done = []

    # Device half deliberately SLOWER than the host half: with work
    # queued, the next flush is always ready before the executor frees,
    # so the pipelined arm must observe refills deterministically.
    def prepare(lane, items):
        time.sleep(0.001)
        return {"n": len(items)}

    def execute(lane, items, prepared=None):
        time.sleep(0.006)
        for it in items:
            it.slot.set("ok")
            done.append(it)

    mb = MicroBatcher(execute, max_batch=2, max_delay_s=0.001,
                      max_queue=64, prepare=prepare,
                      pipeline_depth=pipeline_depth, **kw)
    return mb, done


@pytest.mark.parametrize("depth,expect_refills", [(0, False), (1, True)])
def test_midflight_refill_vs_drain_barrier(depth, expect_refills):
    mb, done = _sleepy_batcher(depth)
    try:
        slots = []

        def feed():
            for _ in range(8):
                while True:
                    s = mb.try_submit("lane", {"cost_units": 1.0})
                    if s is not None:
                        slots.append(s)
                        break
                    time.sleep(0.0005)

        threads = [threading.Thread(target=feed) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        for s in slots:
            assert s.result(timeout=30.0) == "ok"
        assert len(done) == 24
        refills = mb.stats["refills"]
        if expect_refills:
            # Sustained same-lane load MUST overlap: at least one flush
            # staged while the executor was still busy.
            assert refills > 0
        else:
            # The drain barrier structurally cannot refill.
            assert refills == 0
    finally:
        mb.close()


def test_max_observed_depth_counts_inflight_items():
    started = threading.Event()
    release = threading.Event()

    def execute(lane, items):
        started.set()
        release.wait(timeout=30.0)
        for it in items:
            it.slot.set("ok")

    mb = MicroBatcher(execute, max_batch=2, max_delay_s=0.0, max_queue=64)
    try:
        s1 = [mb.try_submit("k", {}) for _ in range(2)]
        assert started.wait(timeout=10.0)
        # Two items are INSIDE execute (not queued); three more queue up.
        s2 = [mb.try_submit("k", {}) for _ in range(3)]
        assert mb.depth() <= 3
        assert mb.stats["max_observed_depth"] >= 5
        release.set()
        for s in s1 + s2:
            assert s.result(timeout=30.0) == "ok"
    finally:
        release.set()
        mb.close()


def test_lane_depth_and_padding_stats_exported():
    class _Key:
        def __init__(self, shape):
            self.shape = shape
            self.filter_name = "blur3"

        def __eq__(self, other):
            return isinstance(other, _Key) and self.shape == other.shape

        def __hash__(self):
            return hash(self.shape)

    bucket = _Key((1, 128, 128))
    mb = MicroBatcher(
        lambda lane, items: [it.slot.set("ok") for it in items],
        max_batch=4, max_delay_s=0.01, max_queue=16, start=False,
        lane_of=lambda k: bucket)
    slots = [mb.try_submit(_Key((1, 100, 120)), {}),
             mb.try_submit(_Key((1, 97, 126)), {})]
    # Queued, not started: the per-lane depth gauge mirrors the queue.
    assert mb.stats["lane_depth:1x128x128:blur3"] == 2
    mb.start()
    for s in slots:
        assert s.result(timeout=30.0) == "ok"
    mb.close()
    # Mixed-shape flush at the bucket extent: pad waste is visible, and
    # the drained lane's depth key is retired (bounded cardinality).
    assert mb.stats["pad_waste_ratio"] > 0.0
    assert "lane_depth:1x128x128:blur3" not in mb.stats
