"""Single-device JAX paths vs the NumPy oracle (bit-exact golden tests)."""

import jax.numpy as jnp
import numpy as np
import pytest

from parallel_convolution_tpu.ops import conv, filters, oracle
from parallel_convolution_tpu.utils import imageio


def _planar_f32(img_u8):
    return jnp.asarray(imageio.interleaved_to_planar(img_u8), jnp.float32)


def _run_oracle(img_u8, filt, iters):
    return oracle.run_serial_u8(img_u8, filt, iters)


@pytest.mark.parametrize("name", ["blur3", "gaussian5", "edge3", "edge5",
                                  "sharpen3", "box3"])
@pytest.mark.parametrize("fixture", ["grey_small", "rgb_small"])
def test_shifted_bitexact_vs_oracle(request, fixture, name):
    img = request.getfixturevalue(fixture)
    filt = filters.get_filter(name)
    want = _run_oracle(img, filt, 3)
    got = np.asarray(conv.run_u8(imageio.interleaved_to_planar(img), filt, 3))
    np.testing.assert_array_equal(imageio.planar_to_interleaved(got), want)


@pytest.mark.parametrize("fixture", ["grey_odd", "rgb_odd"])
def test_odd_shapes_bitexact(request, fixture):
    img = request.getfixturevalue(fixture)
    filt = filters.get_filter("blur3")
    want = _run_oracle(img, filt, 7)
    got = np.asarray(conv.run_u8(imageio.interleaved_to_planar(img), filt, 7))
    np.testing.assert_array_equal(imageio.planar_to_interleaved(got), want)


def test_zero_iters_is_identity(grey_small):
    filt = filters.get_filter("blur3")
    got = np.asarray(conv.run_u8(imageio.interleaved_to_planar(grey_small), filt, 0))
    np.testing.assert_array_equal(got[0], grey_small)


def test_xla_conv_path_matches_oracle_quantized(grey_small):
    # conv_general_dilated may reassociate, but for the dyadic blur3 the
    # accumulation is exact, so even 100 quantized iterations stay identical.
    filt = filters.get_filter("blur3")
    want = _run_oracle(grey_small, filt, 100)
    x = _planar_f32(grey_small)
    got = np.asarray(conv.iterate_u8(x, filt, 100, use_xla_conv=True))
    np.testing.assert_array_equal(got[0].astype(np.uint8), want)


def test_xla_conv_close_to_shifted_nondyadic(rgb_small):
    filt = filters.get_filter("box3")  # 1/9 taps: non-dyadic
    x = _planar_f32(rgb_small)
    a = np.asarray(conv.correlate_shifted(x, filt))
    b = np.asarray(conv.correlate_xla_conv(x, filt))
    np.testing.assert_allclose(a, b, atol=1e-3)


def test_f32_mode_no_quantization(grey_small):
    filt = filters.get_filter("jacobi3")
    x = _planar_f32(grey_small)
    got = np.asarray(conv.iterate_f32(x, filt, 5))
    want = oracle.run_serial_f32(grey_small.astype(np.float32), filt, 5)
    np.testing.assert_array_equal(got[0], want)


def test_100_iteration_golden_grey(grey_small):
    # The reference's canonical workload is 100 iterations (BASELINE).
    filt = filters.get_filter("blur3")
    want = _run_oracle(grey_small, filt, 100)
    got = np.asarray(conv.run_u8(imageio.interleaved_to_planar(grey_small), filt, 100))
    np.testing.assert_array_equal(imageio.planar_to_interleaved(got), want)
