"""Round 13: causal tracing, critical-path attribution, perf sentry.

Covers the acceptance surface of the tracing PR:

* traceparent codec + context propagation (nesting, attach, links,
  disabled-mode no-op with the metrics-style perf guard);
* the end-to-end traced request: a connected span tree with exactly one
  root (frontend → admission → queue → batch → compile|device →
  exchange/compute), batch spans linking every co-batched request, and
  single-flight waiters linking the leader's compile_build span;
* ``/readyz`` readiness semantics (reshaping, queue bound, degrade tier);
* ``scripts/perf_gate.py``: seeded pass, within-noise pass, synthetic
  2x-slower regression, drift-bound flagging.
"""

from __future__ import annotations

import base64
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.obs import events, metrics, trace
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.serving.frontend import InProcessClient
from parallel_convolution_tpu.serving.service import ConvolutionService

SCRIPTS = Path(__file__).resolve().parent.parent / "scripts"


@pytest.fixture(autouse=True)
def _fresh_obs():
    was_enabled = metrics.enabled()
    metrics.set_enabled(True)
    metrics.reset()
    events.deconfigure()
    yield
    events.deconfigure()
    metrics.reset()
    metrics.set_enabled(was_enabled)


def _mesh(shape=(2, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _body(rows=24, cols=36, iters=2, **kw):
    from parallel_convolution_tpu.utils import imageio

    img = imageio.generate_test_image(rows, cols, "grey", seed=1)
    return {
        "image_b64": base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": rows, "cols": cols, "mode": "grey", "filter": "blur3",
        "iters": iters, "backend": "shifted", **kw,
    }


# ------------------------------------------------------ traceparent codec
def test_traceparent_round_trip():
    ctx = trace.SpanContext(trace.new_trace_id(), trace.new_span_id())
    assert trace.parse_traceparent(trace.format_traceparent(ctx)) == ctx


@pytest.mark.parametrize("bad", [
    None, "", "00-abc", 42,
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",      # non-hex trace
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",      # all-zero trace
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",      # all-zero span
    "00-" + "1" * 31 + "-" + "1" * 16 + "-01",      # short trace
    "00-" + "1" * 32 + "-" + "1" * 16,              # missing flags
])
def test_traceparent_malformed_degrades_to_none(bad):
    assert trace.parse_traceparent(bad) is None


# --------------------------------------------------- span context basics
def test_span_nesting_and_record_shape(tmp_path):
    events.configure(tmp_path / "ev.jsonl")
    with trace.span("outer", who="t") as a:
        assert trace.current() == a.context
        with trace.span("inner") as b:
            assert b.context.trace_id == a.context.trace_id
            assert b.parent_id == a.context.span_id
            b.link(a.context, kind="extra")
        assert trace.current() == a.context
    assert trace.current() is None
    recs = events.read_events(tmp_path / "ev.jsonl")
    assert all(events.validate_event(r) == [] for r in recs)
    by_name = {r["name"]: r for r in recs}
    assert by_name["inner"]["parent_id"] == a.context.span_id
    assert by_name["outer"]["parent_id"] == ""
    assert by_name["outer"]["attrs"] == {"who": "t"}
    assert by_name["inner"]["links"] == [
        {**a.context.ref, "kind": "extra"}]
    # children are emitted before parents (end-order); reconstruction
    # is order-independent.
    trees = trace.build_trees(trace.span_records(recs))
    t = trees[a.context.trace_id]
    assert t["roots"] == [a.context.span_id] and not t["orphans"]


def test_span_error_status_and_stack_balance(tmp_path):
    events.configure(tmp_path / "ev.jsonl")
    with pytest.raises(RuntimeError):
        with trace.span("boom"):
            raise RuntimeError("kaput")
    assert trace.current() is None    # the context var unwound
    (rec,) = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    assert rec["status"] == "error"
    assert "kaput" in rec["attrs"]["error"]


def test_attach_and_emit_span(tmp_path):
    events.configure(tmp_path / "ev.jsonl")
    ctx = trace.SpanContext(trace.new_trace_id(), trace.new_span_id())
    with trace.attach(ctx):
        assert trace.current() == ctx
        sid = trace.emit_span("synthetic", trace_id=ctx.trace_id,
                              parent_id=ctx.span_id, start_ts=123.0,
                              dur_s=0.5, detail="x")
    assert trace.current() is None
    (rec,) = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    assert rec["span_id"] == sid and rec["parent_id"] == ctx.span_id
    assert rec["start_ts"] == 123.0 and rec["dur_s"] == 0.5


def test_disabled_mode_is_noop_and_near_zero_overhead(tmp_path):
    """The PCTPU_OBS=0 perf guard (the r11 metrics test, for spans): a
    disabled span() must be one load + one branch returning the shared
    null span — no contextvars, no ids, no allocation per call beyond
    the kwargs dict."""
    events.configure(tmp_path / "ev.jsonl")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("on"):
            pass
    enabled_s = time.perf_counter() - t0
    metrics.set_enabled(False)
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("off"):
            pass
    disabled_s = time.perf_counter() - t0
    assert disabled_s < 0.2                      # < 10 µs/call, absolute
    assert disabled_s < enabled_s * 0.5 + 0.01   # far below the on path
    with trace.span("x") as sp:
        assert sp is trace.NULL_SPAN
        assert trace.current() is None
        sp.set(a=1)
        sp.link({"trace_id": "t", "span_id": "s"})
    assert trace.emit_span("y", trace_id="t") is None
    recs = events.read_events(tmp_path / "ev.jsonl")
    assert [r["name"] for r in trace.span_records(recs)] == ["on"] * n


# --------------------------------------------- end-to-end traced request
def _traced_service(tmp_path, mesh=None, **kw):
    events.configure(tmp_path / "ev.jsonl")
    kw.setdefault("max_delay_s", 0.05)
    svc = ConvolutionService(mesh or _mesh(), max_batch=4, **kw)
    return svc, InProcessClient(svc)


def test_traced_request_yields_connected_single_root_tree(tmp_path):
    """THE acceptance tree: frontend → admission → queue → batch →
    compile|device → exchange/compute, exactly one root per trace, batch
    span linking every co-batched request."""
    svc, client = _traced_service(tmp_path)
    results = []

    def go(i):
        results.append(client.request(
            dict(_body(), request_id=f"q{i}"), timeout=60))

    threads = [threading.Thread(target=go, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    svc.close()
    for s, r in results:
        assert s == 200 and r["ok"], r.get("detail")
        assert r["trace_id"]
    spans = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    trees = trace.build_trees(spans)
    resp_tids = {r["trace_id"] for _, r in results}
    assert resp_tids <= set(trees)
    for tid in resp_tids:
        t = trees[tid]
        assert len(t["roots"]) == 1, f"trace {tid} roots {t['roots']}"
        assert t["orphans"] == []
        root = t["spans"][t["roots"][0]]
        assert root["name"] == "request"
        kid_names = {t["spans"][k]["name"]
                     for k in t["children"].get(root["span_id"], [])}
        assert {"admission", "queue"} <= kid_names
    # Batch spans: every completed request's trace is linked by a batch.
    linked = set()
    batch_owner_trees = []
    for tid, t in trees.items():
        for sid, r in t["spans"].items():
            if r["name"] == "batch":
                linked.update(l["trace_id"] for l in r.get("links", []))
                batch_owner_trees.append((t, sid))
    assert resp_tids <= linked
    # The payer's tree owns compile/device, device owns the attribution
    # leaves (obs on: record_step emitted exchange/compute).
    t, bsid = batch_owner_trees[0]
    batch_kids = {t["spans"][k]["name"]: t["spans"][k]
                  for k in t["children"][bsid]}
    assert {"compile", "copy_in", "device", "copy_out"} <= set(batch_kids)
    dev_kids = {t["spans"][k]["name"]
                for k in t["children"][batch_kids["device"]["span_id"]]}
    assert {"exchange", "compute"} <= dev_kids


def test_traceparent_adopts_upstream_trace(tmp_path):
    svc, client = _traced_service(tmp_path)
    up = trace.SpanContext(trace.new_trace_id(), trace.new_span_id())
    s, r = client.request(
        dict(_body(), request_id="tp1",
             traceparent=trace.format_traceparent(up)), timeout=60)
    svc.close()
    assert s == 200 and r["ok"]
    assert r["trace_id"] == up.trace_id
    spans = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    trees = trace.build_trees(spans)
    t = trees[up.trace_id]
    # The request span parents to the REMOTE caller span, which is
    # absent from this log: reconstruction roots it (remote_parent),
    # never orphans it.
    assert len(t["roots"]) == 1 and t["orphans"] == []
    root = t["spans"][t["roots"][0]]
    assert root["name"] == "request"
    assert root["parent_id"] == up.span_id
    assert root["attrs"].get("remote_parent") is True


def test_rejection_carries_trace_id(tmp_path):
    svc, client = _traced_service(tmp_path)
    bad = dict(_body(), iters=0, request_id="bad0")   # invalid contract
    s, r = client.request(bad, timeout=60)
    svc.close()
    assert s == 400 and r["rejected"] == "invalid"
    assert r["trace_id"]
    spans = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    trees = trace.build_trees(spans)
    t = trees[r["trace_id"]]
    assert len(t["roots"]) == 1 and t["orphans"] == []
    names = {sp["name"] for sp in t["spans"].values()}
    assert names == {"request", "admission"}   # shed before the queue
    adm = next(sp for sp in t["spans"].values()
               if sp["name"] == "admission")
    assert adm["attrs"]["outcome"] == "invalid"


def test_single_flight_waiter_links_leader_compile_span(tmp_path):
    """Two concurrent cold requests for one key: the leader's trace owns
    the compile_build span; the waiter's compile span LINKS it."""
    from parallel_convolution_tpu.serving.engine import WarmEngine

    events.configure(tmp_path / "ev.jsonl")
    eng = WarmEngine(_mesh(), fallback=False)
    key = eng.key_for((1, 24, 36), iters=1)
    gate = threading.Event()
    inner = eng._build_entry

    def slow_build(k):
        gate.wait(10)          # hold the leader until the waiter queues
        return inner(k)

    eng._build_entry = slow_build
    ctxs = {}

    def run(who):
        with trace.span("compile") as sp:
            ctxs[who] = sp.context
            eng.entry(key)

    t1 = threading.Thread(target=run, args=("a",))
    t1.start()
    # The waiter must arrive while the build is in flight.
    for _ in range(200):
        if eng.stats["misses"] >= 1:
            break
        time.sleep(0.01)
    t2 = threading.Thread(target=run, args=("b",))
    t2.start()
    for _ in range(200):
        if eng.stats["single_flight_waits"] >= 1:
            break
        time.sleep(0.01)
    gate.set()
    t1.join(30)
    t2.join(30)
    assert eng.stats["compiles"] == 1
    assert eng.stats["single_flight_waits"] >= 1
    spans = trace.span_records(events.read_events(tmp_path / "ev.jsonl"))
    builds = [s for s in spans if s["name"] == "compile_build"]
    assert len(builds) == 1
    waiters = [s for s in spans if s["name"] == "compile"
               and any(l.get("kind") == "single_flight"
                       for l in s.get("links", []))]
    assert waiters, "waiter span did not link the leader's build"
    assert waiters[0]["links"][0]["span_id"] == builds[0]["span_id"]
    # And the entry remembers who paid (trace_report's critical path).
    assert eng.entry(key).compile_ref == {
        "trace_id": builds[0]["trace_id"],
        "span_id": builds[0]["span_id"]}


def test_zero_overhead_disabled_serving_path(tmp_path):
    """PCTPU_OBS=0 end-to-end: a served request emits NO span events and
    stamps an empty trace_id — and nothing crashes on the null spans."""
    metrics.set_enabled(False)
    svc, client = _traced_service(tmp_path)
    s, r = client.request(dict(_body(), request_id="d0"), timeout=60)
    svc.close()
    assert s == 200 and r["ok"]
    assert r["trace_id"] == ""
    assert trace.span_records(
        events.read_events(tmp_path / "ev.jsonl")) == []


# ----------------------------------------------------------- readiness
def test_readyz_reflects_reshape_queue_and_degrade(tmp_path):
    svc, client = _traced_service(tmp_path)
    try:
        status, payload = client.readyz()
        assert status == 200 and payload["ok"]
        assert payload["queue_depth"] == 0
        assert payload["queue_bound"] == svc.batcher.max_queue
        assert payload["degraded"] == []
        # Reshape in progress -> 503 with the reason visible.
        svc._reshaping = True
        status, payload = client.readyz()
        assert status == 503 and payload["reshaping"] is True
        svc._reshaping = False
        # Queue at the admission bound -> 503 (submissions would shed).
        orig_depth = svc.batcher.depth
        svc.batcher.depth = lambda: svc.batcher.max_queue
        status, payload = client.readyz()
        assert status == 503 and payload["queue_full"] is True
        svc.batcher.depth = orig_depth
        # A degraded resident tier is REPORTED but keeps readiness true.
        s, r = client.request(dict(_body(), request_id="w0"), timeout=60)
        assert s == 200
        entry = next(iter(svc.engine._entries.values()))
        entry.effective_backend = "xla_conv"   # simulate a degraded key
        status, payload = client.readyz()
        assert status == 200
        assert payload["degraded"] == [
            {"requested": "shifted", "effective": "xla_conv"}]
    finally:
        svc.close()


# ------------------------------------------------------- perf sentry
def _gate(*args):
    p = subprocess.run(
        [sys.executable, str(SCRIPTS / "perf_gate.py"), *args],
        capture_output=True, text=True, cwd=str(SCRIPTS.parent))
    return p.returncode, p.stdout, p.stderr


def _row(tmp_path, name, gpx, **kw):
    p = tmp_path / name
    p.write_text(json.dumps({
        "workload": "bench blur3 48x64x1 2 iters",
        "plan_key": "k1", "backend": "shifted",
        "effective_backend": "shifted", "mesh": "2x4",
        "gpixels_per_s": gpx, **kw}))
    return str(p)

def test_perf_gate_seed_pass_regress_and_noise(tmp_path):
    hist = str(tmp_path / "hist.jsonl")
    base = _row(tmp_path, "base.json", 1.0)
    # Seed: no history -> recorded, gate passes.
    rc, out, err = _gate("--history", hist, "--row", base, "--update",
                         "--quiet")
    assert rc == 0, (out, err)
    assert "seeded" in Path(hist).read_text()
    # Within-noise rerun (same number) passes.
    rc, *_ = _gate("--history", hist, "--row", base, "--quiet")
    assert rc == 0
    # 10% down with a 30% floor: still within the gate.
    rc, *_ = _gate("--history", hist, "--row",
                   _row(tmp_path, "near.json", 0.9), "--quiet")
    assert rc == 0
    # The synthetic 2x-slower row exits NONZERO (the acceptance demo).
    rc, out, _ = _gate("--history", hist, "--row",
                       _row(tmp_path, "slow.json", 0.5))
    assert rc == 1 and "regression" in out
    # A different key has no baseline: seeded, not judged against k1.
    rc, *_ = _gate("--history", hist, "--row",
                   _row(tmp_path, "other.json", 0.01, plan_key="k2"),
                   "--quiet")
    assert rc == 0


def test_perf_gate_noise_widens_threshold(tmp_path):
    hist = Path(tmp_path / "hist.jsonl")
    # A noisy history: rel stdev ~20% -> threshold 3*0.2=0.6 > floor.
    with open(hist, "a") as f:
        for v in (1.0, 0.7, 1.3, 0.8, 1.2):
            f.write(json.dumps({"key": "k1|shifted|2x4",
                                "gpixels_per_s": v}) + "\n")
    # 0.55 is 45% below the median (1.0): fails the 0.3 floor but sits
    # inside the noise-widened gate.
    rc, *_ = _gate("--history", str(hist), "--row",
                   _row(tmp_path, "r.json", 0.55), "--quiet")
    assert rc == 0
    rc, *_ = _gate("--history", str(hist), "--row",
                   _row(tmp_path, "r2.json", 0.55), "--quiet",
                   "--noise-mult", "0.0")
    assert rc == 1                     # floor-only: the same row fails


def test_perf_gate_drift_bound(tmp_path):
    snap = {"metrics": [{
        "name": "pctpu_plan_drift_ratio", "kind": "gauge",
        "series": [
            {"labels": {"key": "k1", "backend": "shifted"}, "value": 1.2},
            {"labels": {"key": "k2", "backend": "pallas"}, "value": 20.0},
        ]}]}
    sp = tmp_path / "snap.json"
    sp.write_text(json.dumps(snap))
    hist = str(tmp_path / "hist.jsonl")
    rc, out, _ = _gate("--history", hist, "--drift-metrics", str(sp),
                       "--drift-bound", "10")
    assert rc == 1 and "k2" in out     # 20x off the model: flagged
    rc, *_ = _gate("--history", hist, "--drift-metrics", str(sp),
                   "--drift-bound", "25", "--quiet")
    assert rc == 0                     # within the wider bound


# -------------------------------------------------------- trace report
def test_trace_report_script_on_served_traffic(tmp_path):
    """The CLI end of the tentpole: reconstructs the smoke's invariants
    (rc 0, no orphans) and writes parseable Chrome trace JSON."""
    svc, client = _traced_service(tmp_path)
    for i in range(3):
        s, r = client.request(dict(_body(), request_id=f"c{i}"),
                              timeout=60)
        assert s == 200, r
    svc.close()
    out = tmp_path / "report.json"
    chrome = tmp_path / "chrome.json"
    p = subprocess.run(
        [sys.executable, str(SCRIPTS / "trace_report.py"),
         "--events", str(tmp_path / "ev.jsonl"), "--out", str(out),
         "--chrome", str(chrome), "--quiet"],
        capture_output=True, text=True, cwd=str(SCRIPTS.parent))
    assert p.returncode == 0, (p.stdout, p.stderr)
    rep = json.loads(out.read_text())
    assert rep["orphan_spans"] == 0 and rep["roots_per_trace_ok"]
    assert rep["traces"] >= 3 and rep["batches"]
    b = rep["batches"][0]
    assert b["device_ms"] >= 0 and b["linked_traces"]
    assert b["exposed_exchange_fraction_of_device"] is not None
    ev = json.loads(chrome.read_text())["traceEvents"]
    assert any(e.get("ph") == "X" and e["name"] == "request" for e in ev)
    # Critical paths root at the request span.
    for path in rep["critical_paths"].values():
        assert path[0]["name"] == "request"
