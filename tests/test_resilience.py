"""Resilience subsystem: fault injection, classified retry, degradation,
hardened checkpoints, supervised legs (tests run on the 8-virtual-device
CPU mesh — no TPU needed).

The two acceptance properties from the resilience PR:

* under an injected mid-run kill at EVERY checkpoint fault site, the
  resumed output is byte-identical to an uninterrupted run
  (test_kill_at_each_checkpoint_site_resume_bitexact);
* under an injected ``backend_compile`` fault, ``fallback=True``
  completes byte-identically on the next backend in the chain and the
  emitted bench row records the effective backend
  (test_backend_compile_fault_degrades_bitexact,
  test_bench_fallback_row_records_degradation).
"""

from __future__ import annotations

import json
import subprocess
import sys
import zlib
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.resilience import degrade, faults, retry
from parallel_convolution_tpu.resilience.supervisor import (
    Leg, Supervisor, legs_from_json,
)
from parallel_convolution_tpu.utils import bench, checkpoint, imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


def _prepare(img, m, filt):
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    return step._prepare(x, m, filt.radius)


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


# ---------------------------------------------------------------- faults


def test_fault_point_is_noop_without_plan():
    for site in sorted(faults.KNOWN_SITES):
        faults.fault_point(site)  # must not raise, count, or allocate


def test_plan_hit_indexed_trigger():
    with faults.injected("checkpoint_write_shard:2") as plan:
        faults.fault_point("checkpoint_write_shard")  # hit 1: no fire
        with pytest.raises(faults.InjectedFault) as ei:
            faults.fault_point("checkpoint_write_shard")
        assert ei.value.site == "checkpoint_write_shard"
        assert ei.value.hit == 2
        assert ei.value.transient
        faults.fault_point("checkpoint_write_shard")  # hit 3: no fire
        assert plan.fired == [("checkpoint_write_shard", 2)]
        # sites not in the plan are free
        faults.fault_point("io_read")
        assert plan.hits("io_read") == 0


def test_plan_range_every_and_terminal_triggers():
    with faults.injected("io_read:2+,device_probe:*,backend_compile:1!"):
        faults.fault_point("io_read")
        for _ in range(3):
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("io_read")
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("device_probe")
        with pytest.raises(faults.InjectedFault) as ei:
            faults.fault_point("backend_compile")
        assert not ei.value.transient
        assert retry.classify(ei.value) == retry.TERMINAL


def test_plan_probability_deterministic_per_seed():
    def fires(seed):
        plan = faults.plan_from_spec("io_read:p0.5", seed=seed)
        out = []
        for _ in range(50):
            try:
                plan.check("io_read")
                out.append(False)
            except faults.InjectedFault:
                out.append(True)
        return out

    assert fires(7) == fires(7)
    assert any(fires(7)) and not all(fires(7))


def test_bad_specs_rejected():
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.plan_from_spec("not_a_site:1")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.plan_from_spec("io_read")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.plan_from_spec("io_read:0")
    with pytest.raises(ValueError, match="empty"):
        faults.plan_from_spec("  ,  ")


def test_plan_from_env():
    assert faults.plan_from_env({}) is None
    plan = faults.plan_from_env(
        {"PCTPU_FAULTS": "io_read:1", "PCTPU_FAULT_SEED": "3"})
    assert plan.seed == 3 and "io_read" in plan.rules


# ----------------------------------------------------------------- retry


def test_classify_taxonomy():
    T, X = retry.TRANSIENT, retry.TERMINAL
    assert retry.classify(RuntimeError("UNAVAILABLE: Socket closed")) == T
    assert retry.classify(RuntimeError("DEADLINE_EXCEEDED over tunnel")) == T
    assert retry.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: probe OOM")) == T
    assert retry.classify(RuntimeError(
        "INTERNAL: Mosaic failed to compile")) == T
    # regression: a Mosaic crash whose text mentions vector *shapes* is
    # still the transient compile-crash class, not a contract error
    assert retry.classify(RuntimeError(
        "INTERNAL: Mosaic ... unsupported vector.shape_cast")) == T
    assert retry.classify(TimeoutError()) == T
    assert retry.classify(ConnectionError("reset")) == T
    # terminal: retrying burns chip time forever
    assert retry.classify(ValueError("checkpoint grid [2,2] != [1,4]")) == X
    assert retry.classify(ValueError("checkpoint config mismatch")) == X
    assert retry.classify(RuntimeError("magic_round_guard MISMATCH")) == X
    assert retry.classify(TypeError("bad shape")) == X
    assert retry.classify(RuntimeError("some unclassified novelty")) == X
    assert retry.classify(faults.InjectedFault("io_read", 1)) == T
    assert retry.classify(
        faults.InjectedFault("io_read", 1, transient=False)) == X


def test_with_retry_recovers_and_schedules_deterministically():
    calls, slept = [], []
    policy = retry.RetryPolicy(max_attempts=4, base_delay=1.0,
                               max_delay=60.0, seed=5)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: tunnel down")
        return "ok"

    assert retry.with_retry(flaky, policy, sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert slept == policy.delays()[:2]
    # deterministic: same policy/failure pattern -> same schedule
    assert policy.delays() == retry.RetryPolicy(
        max_attempts=4, base_delay=1.0, max_delay=60.0, seed=5).delays()
    # capped exponential shape: nondecreasing raw backoff, jitter in
    # [0.5, 1.0] of the raw value
    for k, d in enumerate(policy.delays(), start=1):
        raw = min(60.0, 1.0 * 2.0 ** (k - 1))
        assert 0.5 * raw <= d <= raw


def test_with_retry_terminal_raises_immediately():
    slept = []
    with pytest.raises(ValueError):
        retry.with_retry(
            lambda: (_ for _ in ()).throw(ValueError("shape wrong")),
            retry.RetryPolicy(max_attempts=5), sleep=slept.append)
    assert slept == []


def test_with_retry_exhaustion():
    slept = []
    with pytest.raises(retry.RetryExhausted):
        retry.with_retry(
            lambda: (_ for _ in ()).throw(TimeoutError("probe")),
            retry.RetryPolicy(max_attempts=3, base_delay=0.1),
            sleep=slept.append)
    assert len(slept) == 2  # no sleep after the final attempt


# ---------------------------------------------- hardened checkpoints


def _make_snapshots(tmp_path, img, m, filt, total=6, every=2):
    """run_checkpointed leaving snapshots at `every` boundaries."""
    xs, valid_hw, _ = _prepare(img, m, filt)
    out = checkpoint.run_checkpointed(
        xs, filt, total_iters=total, mesh=m, valid_hw=valid_hw,
        ckpt_dir=tmp_path / "ck", every=every)
    return tmp_path / "ck", valid_hw, out


def test_meta_records_shard_crcs(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    ck, _, _ = _make_snapshots(tmp_path, grey_odd, m, filt)
    meta = checkpoint.load_meta(ck)
    shards = meta["shards"]
    assert sorted(shards) == sorted(
        f"shard_{r}_{c}.npy" for r in range(2) for c in range(2))
    snap = ck / f"it_{meta['iters_done']:08d}"
    for name, rec in shards.items():
        raw = (snap / name).read_bytes()
        assert len(raw) == rec["bytes"]
        assert zlib.crc32(raw) == rec["crc32"]


@pytest.mark.parametrize("damage", ["missing", "truncated", "bitflip"])
def test_load_state_detects_torn_snapshot(tmp_path, grey_odd, damage):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    ck, _, _ = _make_snapshots(tmp_path, grey_odd, m, filt)
    latest = ck / (ck / "LATEST").read_text().strip()
    victim = latest / "shard_1_0.npy"
    if damage == "missing":
        victim.unlink()  # the multi-host prune race: meta without shards
    elif damage == "truncated":
        victim.write_bytes(victim.read_bytes()[:-8])
    else:
        raw = bytearray(victim.read_bytes())
        raw[-1] ^= 0xFF
        victim.write_bytes(bytes(raw))
    with pytest.raises(checkpoint.CheckpointCorrupt, match="shard_1_0"):
        checkpoint.load_state(ck, m)
    # fallback walks to the older snapshot, which still validates
    with pytest.warns(checkpoint.CheckpointWarning, match="torn"):
        _, meta = checkpoint.load_state(ck, m, fallback=True)
    assert meta["iters_done"] == 2  # snapshots were at 2 and 4


def test_run_checkpointed_resumes_through_torn_latest(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    want = oracle.run_serial_u8(grey_odd, filt, 9)
    ck, valid_hw, _ = _make_snapshots(tmp_path, grey_odd, m, filt,
                                      total=6, every=2)
    latest = ck / (ck / "LATEST").read_text().strip()
    (latest / "shard_0_1.npy").unlink()
    with pytest.warns(checkpoint.CheckpointWarning):
        out = checkpoint.run_checkpointed(
            None, filt, total_iters=9, mesh=m, valid_hw=valid_hw,
            ckpt_dir=ck, every=2)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    np.testing.assert_array_equal(got[0], want)


def test_run_checkpointed_fresh_when_every_snapshot_torn(tmp_path, grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    ck, valid_hw, _ = _make_snapshots(tmp_path, grey_odd, m, filt)
    for snap in ck.glob("it_*"):
        (snap / "shard_0_0.npy").unlink()
    xs, valid_hw, _ = _prepare(grey_odd, m, filt)
    with pytest.warns(checkpoint.CheckpointWarning, match="starting fresh"):
        out = checkpoint.run_checkpointed(
            xs, filt, total_iters=6, mesh=m, valid_hw=valid_hw,
            ckpt_dir=ck, every=2)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    np.testing.assert_array_equal(got[0], want)


# Acceptance: a kill at EVERY checkpoint fault site, then resume ->
# byte-identical to an uninterrupted run.  Geometry: (2,2) mesh -> 4
# shard writes per save; every=3, total=8 -> saves at 3 and 6.
# checkpoint_write_shard hits 1/3 tear the first save, hit 5 the second;
# checkpoint_write_meta hits 1/2 are the first save's meta write and
# LATEST flip, hits 3/4 the second save's.
@pytest.mark.parametrize("spec", [
    "checkpoint_write_shard:1",
    "checkpoint_write_shard:3",
    "checkpoint_write_shard:5",
    "checkpoint_write_meta:1",
    "checkpoint_write_meta:2",
    "checkpoint_write_meta:3",
    "checkpoint_write_meta:4",
])
def test_kill_at_each_checkpoint_site_resume_bitexact(tmp_path, grey_odd,
                                                      spec):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    total, every = 8, 3
    want = oracle.run_serial_u8(grey_odd, filt, total)
    ck = tmp_path / "ck"
    with faults.injected(spec) as plan:
        xs, valid_hw, _ = _prepare(grey_odd, m, filt)
        with pytest.raises(faults.InjectedFault):
            checkpoint.run_checkpointed(
                xs, filt, total_iters=total, mesh=m, valid_hw=valid_hw,
                ckpt_dir=ck, every=every)
        assert plan.fired  # the kill really happened where we asked
    # the restarted process: fresh input, no plan, same ckpt dir
    xs2, valid_hw, _ = _prepare(grey_odd, m, filt)
    out = checkpoint.run_checkpointed(
        xs2, filt, total_iters=total, mesh=m, valid_hw=valid_hw,
        ckpt_dir=ck, every=every)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    np.testing.assert_array_equal(got[0], want)


# ------------------------------------------------- backend degradation


def test_degradation_chains():
    assert degrade.degradation_chain("pallas_rdma") == (
        "pallas_rdma", "pallas", "shifted")
    assert degrade.degradation_chain("pallas_sep") == (
        "pallas_sep", "pallas", "shifted")
    assert degrade.degradation_chain("shifted") == ("shifted",)


def test_backend_compile_fault_degrades_bitexact(grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    want = oracle.run_serial_u8(grey_odd, filt, 3)
    with faults.injected("backend_compile:1"):
        xs, valid_hw, _ = _prepare(grey_odd, m, filt)
        with pytest.warns(degrade.BackendDegradedWarning,
                          match="'pallas' degraded to 'shifted'"):
            out = step.iterate_prepared(
                xs, filt, 3, m, valid_hw, backend="pallas", fallback=True)
    got = np.asarray(out)[:, : valid_hw[0], : valid_hw[1]].astype(np.uint8)
    np.testing.assert_array_equal(got[0], want)


def test_terminal_probe_failure_does_not_degrade():
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    with faults.injected("backend_compile:1!"):  # terminal compile fault
        with pytest.raises(faults.InjectedFault):
            degrade.resolve_backend(m, filt, "pallas")


def test_probe_cached_once_per_process():
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    assert degrade.resolve_backend(m, filt, "shifted") == "shifted"
    # a plan installed AFTER the successful probe must not re-fire: the
    # (backend, config) verdict is cached per process
    with faults.injected("backend_compile:*"):
        assert degrade.resolve_backend(m, filt, "shifted") == "shifted"


def test_model_records_effective_backend(grey_odd):
    from parallel_convolution_tpu.models import ConvolutionModel

    m = _mesh((2, 2))
    with faults.injected("backend_compile:1"):
        with pytest.warns(degrade.BackendDegradedWarning):
            model = ConvolutionModel(filt="blur3", mesh=m, backend="pallas",
                                     fallback=True)
            got = model.run_image(grey_odd, 2)
    assert model.effective_backend == "shifted"
    want = oracle.run_serial_u8(grey_odd, filters.get_filter("blur3"), 2)
    np.testing.assert_array_equal(got, want)


# ----------------------------------------------------- bench stamping


def test_bench_row_stamps_platform_and_effective_backend():
    filt = filters.get_filter("blur3")
    row = bench.bench_iterate((32, 32), filt, 2, mesh=_mesh((2, 2)),
                              backend="shifted", reps=1)
    assert row["platform"] == "cpu"
    assert row["effective_backend"] == "shifted"
    assert row["backend"] == "shifted"


def test_bench_fallback_row_records_degradation():
    filt = filters.get_filter("blur3")
    with faults.injected("backend_compile:1"):
        with pytest.warns(degrade.BackendDegradedWarning):
            row = bench.bench_iterate((32, 32), filt, 2, mesh=_mesh((2, 2)),
                                      backend="pallas", reps=1,
                                      fallback=True)
    assert row["backend"] == "pallas"          # what was asked for
    assert row["effective_backend"] == "shifted"  # what actually ran
    assert row["platform"] == "cpu"


# ------------------------------------------------- other fault sites


def test_halo_exchange_fault_site(grey_odd):
    filt = filters.get_filter("blur3")
    m = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    # fresh geometry so the runner is traced (not served from lru_cache)
    with faults.injected("halo_exchange:1"):
        with pytest.raises(faults.InjectedFault):
            step.sharded_iterate(x[:, :35, :29], filt, 1, mesh=m)


def test_io_read_fault_site(tmp_path):
    from parallel_convolution_tpu.utils import sharded_io

    img = imageio.generate_test_image(24, 40, "grey", seed=11)
    raw = tmp_path / "img.raw"
    imageio.write_raw(raw, img)
    m = _mesh((2, 2))
    with faults.injected("io_read:1"):
        with pytest.raises(Exception, match="injected fault at 'io_read'"):
            sharded_io.load_sharded(str(raw), 24, 40, "grey", m)


def test_device_probe_fault_site_recovers_under_retry():
    from parallel_convolution_tpu.utils import platform

    slept = []
    with faults.injected("device_probe:1"):
        note = retry.with_retry(
            platform.ensure_live_backend,
            retry.RetryPolicy(max_attempts=2, base_delay=0.01),
            sleep=slept.append)
    assert len(slept) == 1  # first probe died injected, second healed
    assert note is None  # CPU backend is alive


# -------------------------------------------------------- supervisor


_FLAKY = """\
import os, sys
marker, out = sys.argv[1], sys.argv[2]
if not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(1)
open(out, "w").write("done leg")
"""


def test_supervisor_retries_transient_leg_to_done(tmp_path):
    out = tmp_path / "leg.out.artifact"
    leg = Leg(name="flaky",
              cmd=[sys.executable, "-c", _FLAKY,
                   str(tmp_path / "marker"), str(out)],
              done_file=str(out), done_pattern="done")
    sup = Supervisor([leg], tmp_path / "state",
                     policy=retry.RetryPolicy(max_attempts=3,
                                              base_delay=0.01),
                     sleep=lambda s: None, log=lambda m: None)
    assert sup.run() == 0
    status = json.loads((tmp_path / "state" / "status.json").read_text())
    assert status["legs"]["flaky"]["state"] == "done"
    assert status["legs"]["flaky"]["attempts"] == 2
    assert status["halt"] is None
    # idempotent re-run: completed legs are skipped
    assert sup.run() == 0


def test_supervisor_terminal_pattern_halts_queue(tmp_path):
    second = tmp_path / "second.txt"
    legs = [
        Leg(name="mismatch",
            cmd=[sys.executable, "-c",
                 "print('\"magic_round_guard\": \"MISMATCH\"')"],
            done_file=str(tmp_path / "never"),
            terminal_pattern='"magic_round_guard": "MISMATCH"'),
        Leg(name="after",
            cmd=[sys.executable, "-c",
                 f"open({str(second)!r}, 'w').write('x')"]),
    ]
    sup = Supervisor(legs, tmp_path / "state",
                     policy=retry.RetryPolicy(max_attempts=3,
                                              base_delay=0.01),
                     sleep=lambda s: None, log=lambda m: None)
    assert sup.run() == 2
    assert (tmp_path / "state" / "HALT").exists()
    assert not second.exists()  # the queue stopped at the terminal leg
    status = json.loads((tmp_path / "state" / "status.json").read_text())
    assert status["halt"]["leg"] == "mismatch"
    # a later run refuses while the sentinel stands (the tunnel_watch
    # HALT_r5c contract, now enforced in one place)
    assert sup.run() == 2


def test_supervisor_exhausted_leg_continues_queue(tmp_path):
    done2 = tmp_path / "two.txt"
    legs = [
        Leg(name="hopeless", cmd=[sys.executable, "-c", "raise SystemExit(1)"]),
        Leg(name="fine",
            cmd=[sys.executable, "-c", f"open({str(done2)!r}, 'w').write('y')"],
            done_file=str(done2)),
    ]
    sup = Supervisor(legs, tmp_path / "state",
                     policy=retry.RetryPolicy(max_attempts=2,
                                              base_delay=0.01),
                     sleep=lambda s: None, log=lambda m: None)
    assert sup.run() == 1
    status = json.loads((tmp_path / "state" / "status.json").read_text())
    assert status["legs"]["hopeless"]["state"] == "exhausted"
    assert status["legs"]["fine"]["state"] == "done"


def test_supervisor_sleeps_the_policy_schedule(tmp_path):
    """One retry implementation: the supervisor's backoff must equal
    RetryPolicy.delays() — not a private derivation of it."""
    policy = retry.RetryPolicy(max_attempts=3, base_delay=0.5, seed=9)
    slept = []
    leg = Leg(name="hopeless",
              cmd=[sys.executable, "-c", "raise SystemExit(1)"])
    sup = Supervisor([leg], tmp_path / "state", policy=policy,
                     sleep=slept.append, log=lambda m: None)
    assert sup.run() == 1
    assert slept == policy.delays()


def test_legs_from_json_validation():
    legs = legs_from_json(
        '[{"name": "a", "cmd": ["true"], "done_file": "x"}]')
    assert legs[0].name == "a"
    with pytest.raises(ValueError, match="unknown leg field"):
        legs_from_json('[{"name": "a", "cmd": ["true"], "bogus": 1}]')
    with pytest.raises(ValueError, match="JSON list"):
        legs_from_json('{"name": "a"}')


# --------------------------------------- end-to-end fault-soak drill


def test_fault_soak_trial_end_to_end(tmp_path):
    """One scripts/soak.py --fault-trial child: inject a checkpoint tear,
    crash, resume, byte-compare — the unit the supervised fault soak
    (--faults N) fans out."""
    from parallel_convolution_tpu.utils.platform import child_env_cpu

    out = tmp_path / "trial.json"
    repo = Path(__file__).resolve().parents[1]
    p = subprocess.run(
        [sys.executable, str(repo / "scripts" / "soak.py"),
         "--fault-trial", "checkpoint_write_shard:2",
         "--trial-seed", "3", "--trial-out", str(out)],
        env=child_env_cpu(8), capture_output=True, text=True, timeout=300,
        cwd=repo)
    assert p.returncode == 0, p.stderr[-2000:]
    row = json.loads(out.read_text())
    assert row["ok"] is True
    assert row["crashed"] is not None  # the injected kill really fired
    assert row["fired"] == [["checkpoint_write_shard", 2]]
