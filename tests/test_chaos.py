"""Durable convergence jobs + chaos transport (round 18, ISSUE 13).

The acceptance properties, all on the 8-virtual-device CPU mesh:

* the fault-site table is DRIFT-GUARDED — every ``fault_point(name)``
  consult in the tree names a registered ``SITE_TABLE`` site and every
  registered site is consulted somewhere;
* the chaos transport injects deterministically (seeded ``PCTPU_FAULTS``
  schedules) and its failures look like real networks: ConnectionError
  drops/black-holes, CorruptReplicaBody garbage, mid-stream breaks;
* corrupt/truncated JSON from a replica is a TYPED transport failure
  (breaker food + failover walk + per-replica counter), never an
  uncaught JSONDecodeError out of the router;
* a resumed converge job's final row is BYTE-IDENTICAL to the
  uninterrupted run — same grid, different grid, jacobi and multigrid;
* ``router.converge`` fails over MID-STREAM: after rows have flowed, a
  transport death walks the surviving ring candidates with the newest
  resume token, stamps ``router: {resumed_from, resume_count}``, and
  delivers exactly ONE final row per request_id;
* a client retry of a mid-stream typed retryable row resumes from the
  router's job ledger instead of iteration 0, and with the pricer armed
  the tenant is charged only the INCREMENTAL work.
"""

from __future__ import annotations

import base64
import json
import re
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.resilience import degrade, faults
from parallel_convolution_tpu.serving import frames, jobs
from parallel_convolution_tpu.serving.chaos import (
    ChaosTransport, modes_from_spec, truncate_frame_bytes,
)
from parallel_convolution_tpu.serving.frontend import (
    decode_converge, encode_stream_row,
)
from parallel_convolution_tpu.serving.router import (
    CorruptReplicaBody, HTTPReplica, InProcessReplica, ReplicaRouter,
    TenantQuotas,
)
from parallel_convolution_tpu.serving.service import (
    ConvolutionService, Rejected, Request, Snapshot,
)
from parallel_convolution_tpu.utils import imageio


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _factory(shape=(1, 2), **kw):
    kw.setdefault("max_delay_s", 0.002)

    def make():
        return ConvolutionService(_mesh(shape), **kw)

    return make


def _converge_body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "jacobi3", "backend": "shifted", "quantize": False,
        "tol": 0.0, "max_iters": 40, "check_every": 10}
    body.update(kw)
    return body


def _chaos_router(n=3, shape=(1, 2), seed=1, modes=None, **kw):
    kw.setdefault("poll_interval_s", 0.05)
    kw.setdefault("breaker_cooldown_s", 0.2)
    reps = [ChaosTransport(InProcessReplica(_factory(shape), name=f"c{i}"),
                           modes=modes, seed=seed + i)
            for i in range(n)]
    return ReplicaRouter(reps, **kw)


# ------------------------------------------------ fault-site drift guard


def test_fault_site_table_is_complete():
    """Every fault_point(name) consult in the tree is a registered
    SITE_TABLE site, and every registered site is consulted somewhere —
    the grammar's documented table can never drift from the code (the
    six compute/IO sites used to live only in DESIGN.md prose)."""
    root = Path(step.__file__).resolve().parents[1]
    referenced: set[str] = set()
    for py in root.rglob("*.py"):
        for m in re.finditer(r"fault_point\(\s*['\"]([a-z_]+)['\"]",
                             py.read_text()):
            referenced.add(m.group(1))
    assert referenced == set(faults.SITE_TABLE), (
        f"fault sites drifted: consulted-but-unregistered "
        f"{sorted(referenced - set(faults.SITE_TABLE))}, "
        f"registered-but-never-consulted "
        f"{sorted(set(faults.SITE_TABLE) - referenced)}")
    assert faults.KNOWN_SITES == frozenset(faults.SITE_TABLE)


def test_transport_sites_parse_in_fault_grammar():
    plan = faults.plan_from_spec(
        "transport_send:2,transport_recv:p0.5,transport_stream:3+,"
        "readyz_probe:*")
    assert set(plan.rules) == {"transport_send", "transport_recv",
                               "transport_stream", "readyz_probe"}
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.plan_from_spec("transport_sned:1")


def test_chaos_mode_spec_parse_and_reject():
    modes = modes_from_spec(
        "transport_send=latency,transport_recv=corrupt")
    assert modes == {"transport_send": "latency",
                     "transport_recv": "corrupt"}
    with pytest.raises(ValueError, match="unknown chaos site"):
        modes_from_spec("transport_sned=drop")
    with pytest.raises(ValueError, match="unknown mode"):
        modes_from_spec("transport_send=corrupt")
    with pytest.raises(ValueError, match="unknown mode"):
        ChaosTransport(object(), {"readyz_probe": "drop"})


# ------------------------------------------------------- chaos transport


def test_chaos_send_drop_is_deterministic():
    rep = ChaosTransport(InProcessReplica(_factory(), name="c0"), seed=0)
    img = _img()
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "blur3", "iters": 1, "request_id": "d1"}
    with faults.injected("transport_send:2"):
        status, wire = rep.request(dict(body))
        assert status == 200 and wire["ok"]
        with pytest.raises(ConnectionError, match="chaos: dropped send"):
            rep.request(dict(body, request_id="d2"))
        status, wire = rep.request(dict(body, request_id="d3"))
        assert status == 200 and wire["ok"]
    assert rep.injected == {"transport_send": 1}
    # The dropped send never reached the replica: exactly 2 completions.
    assert rep.inner.service.stats["completed"] == 2
    rep.close()


def test_chaos_recv_drop_executed_work_dedups_on_retry():
    """transport_recv drop: the work EXECUTED but the response was lost
    — the idempotency case.  A client retry with the same request_id
    must dedup into the first execution, not re-run it."""
    rep = ChaosTransport(InProcessReplica(_factory(), name="c0"), seed=0)
    router = ReplicaRouter([rep], start_health=False)
    img = _img()
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "blur3", "iters": 2, "request_id": "rv1"}
    with faults.injected("transport_recv:1"):
        status, wire = router.request(dict(body))
    # The single replica's response was dropped: typed retryable.
    assert wire["rejected"] == "replica_unavailable" and wire["retryable"]
    svc = rep.inner.service
    assert svc.stats["completed"] == 1   # the work DID execute
    status, wire = router.request(dict(body))   # the client retry
    assert status == 200 and wire["ok"]
    assert svc.stats["completed"] == 1   # deduped, not re-executed
    assert svc.stats["deduped"] == 1
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
    got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                        np.uint8).reshape(img.shape)
    np.testing.assert_array_equal(got, want)
    router.close()


def test_chaos_readyz_flap_marks_unready_then_recovers():
    rep = ChaosTransport(InProcessReplica(_factory(), name="c0"), seed=0)
    router = ReplicaRouter([rep], start_health=False)
    with faults.injected("readyz_probe:1"):
        router.poll_once()
        assert not router._replicas["c0"].ready
        router.poll_once()
        assert router._replicas["c0"].ready
    router.close()


# --------------------------------------- corrupt bodies are typed, counted


class _GarbageHTTP:
    """A minimal HTTP server answering every POST with corrupt JSON."""

    def __init__(self, payload=b"{not json", status=200):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", "0") or 0)
                self.rfile.read(n)
                self.send_response(status)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST  # noqa: N815 — garbage everywhere

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_corrupt_json_is_typed_transport_failure():
    srv = _GarbageHTTP()
    try:
        rep = HTTPReplica(f"http://127.0.0.1:{srv.port}", name="bad")
        with pytest.raises(CorruptReplicaBody, match="unparseable"):
            rep.request({"rows": 1, "cols": 1})
        with pytest.raises(CorruptReplicaBody):
            rep.readyz()
    finally:
        srv.close()


def test_router_fails_over_past_corrupting_replica():
    """The regression the satellite names: a corrupt body is breaker
    food + a failover walk, NOT an uncaught JSONDecodeError out of the
    router — and the per-replica corrupt_responses counter sees it."""
    srv = _GarbageHTTP()
    good = InProcessReplica(_factory(), name="good")
    bad = HTTPReplica(f"http://127.0.0.1:{srv.port}", name="bad")
    router = ReplicaRouter([bad, good], start_health=False)
    img = _img()
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)
    try:
        ok = corrupt_seen = 0
        for i in range(6):
            body = {"image_b64": base64.b64encode(
                np.ascontiguousarray(img).tobytes()).decode("ascii"),
                "rows": img.shape[0], "cols": img.shape[1],
                "mode": "grey", "filter": "blur3", "iters": 1,
                "request_id": f"cj{i}"}
            status, wire = router.request(body)
            assert status == 200 and wire["ok"], wire
            got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                                np.uint8).reshape(img.shape)
            np.testing.assert_array_equal(got, want)
            ok += 1
            if wire["router"]["failovers"] > 0:
                corrupt_seen += 1
        assert ok == 6
        snap = router.snapshot()
        assert snap["replicas"]["bad"]["corrupt_responses"] >= 1
        assert snap["replicas"]["good"]["corrupt_responses"] == 0
    finally:
        router.close()
        srv.close()


# --------------------------------------------------- resume token codec


def test_resume_token_codec_roundtrip_and_rejects():
    state = np.arange(24, dtype=np.float32).reshape(1, 4, 6) / 7.0
    b64, shape = jobs.state_to_wire(state)
    back = jobs.state_from_wire(b64, shape)
    np.testing.assert_array_equal(back, state)
    with pytest.raises(ValueError, match="bytes"):
        jobs.state_from_wire(b64, [1, 4, 7])
    with pytest.raises(ValueError, match="state_shape"):
        jobs.state_from_wire(b64, "nope")
    # through the wire decoder: a malformed token is a typed 400
    body = _converge_body(_img(), resume={"iters": 10, "diff": 1.0,
                                          "work_units": 10.0,
                                          "state_b64": "!!!",
                                          "state_shape": [1, 4, 6]})
    with pytest.raises(ValueError, match="malformed request body"):
        decode_converge(body)


def test_job_ledger_exactly_once_and_identity():
    led = jobs.JobLedger(capacity=4)
    row = {"ok": True, "kind": "snapshot", "iters": 10, "diff": 0.5,
           "work_units": 10.0, "solver": "jacobi",
           "state_b64": jobs.state_to_wire(
               np.zeros((1, 2, 2), np.float32))[0],
           "state_shape": [1, 2, 2]}
    led.observe("r1", "keyA", row)
    assert led.token("r1", "keyA")["iters"] == 10
    # a reused id naming a DIFFERENT config never resumes the old field
    assert led.token("r1", "keyB") is None
    assert led.begin("r1", "keyB") is None
    assert led.finalize("r1") is True
    assert led.finalize("r1") is False          # exactly-once
    assert led.begin("r1", "keyA") is None      # entry dropped on final
    assert led.finalize("r1") is True           # fresh life, fresh final


# ----------------------------------------------- service-level resume


def _progressive_rows(svc, img, rid, **kw):
    kw.setdefault("tol", 0.0)
    kw.setdefault("max_iters", 40)
    kw.setdefault("check_every", 10)
    stream = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False,
                request_id=rid), **kw)
    assert not isinstance(stream, Rejected), stream
    return list(stream)


def test_service_resume_final_bytes_identical():
    img = _img(40, 56, seed=3)
    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    rows = _progressive_rows(svc, img, "u1", carry_state=True)
    assert rows[-1].final
    # snapshots carry the f32 state only when asked; finals never do
    assert all(r.state is not None for r in rows[:-1])
    assert rows[-1].state is None
    uncarried = _progressive_rows(svc, img, "u2")
    assert all(r.state is None for r in uncarried)
    tok = rows[1]   # iters=20, a check_every boundary
    resume = {"iters": tok.iters, "diff": tok.diff,
              "work_units": tok.work_units, "state": tok.state}
    resumed = _progressive_rows(svc, img, "u3", resume=resume)
    assert [r.iters for r in resumed] == [30, 40, 40]
    assert resumed[-1].final
    np.testing.assert_array_equal(resumed[-1].image, rows[-1].image)
    assert resumed[-1].work_units == rows[-1].work_units
    svc.close()


def test_service_resume_across_grids_byte_identical():
    """The token's field reshards onto the resuming replica's OWN grid
    (crop + zero-re-pad is bit-exact — the checkpoint-reshard
    invariant), so a job can resume onto a replica holding a different
    mesh and still produce the uninterrupted run's exact bytes."""
    img = _img(40, 56, seed=3)
    svc_a = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    rows = _progressive_rows(svc_a, img, "g1", carry_state=True)
    tok = rows[0]
    resume = {"iters": tok.iters, "diff": tok.diff,
              "work_units": tok.work_units, "state": tok.state}
    for shape in ((1, 2), (1, 1), (2, 4)):
        svc_b = ConvolutionService(_mesh(shape), max_delay_s=0.002)
        resumed = _progressive_rows(svc_b, img, f"g-{shape}",
                                    resume=resume)
        assert resumed[-1].final
        assert resumed[-1].effective_grid == f"{shape[0]}x{shape[1]}"
        np.testing.assert_array_equal(resumed[-1].image, rows[-1].image)
        svc_b.close()
    svc_a.close()


def test_service_resume_multigrid_byte_identical():
    img = _img(48, 64, seed=3)
    kw = dict(tol=1e-3, max_iters=400, check_every=10)
    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)

    def mg_rows(rid, **extra):
        stream = svc.submit_progressive(
            Request(image=img, filter_name="blur3", quantize=False,
                    solver="multigrid", request_id=rid), **kw, **extra)
        assert not isinstance(stream, Rejected), stream
        return list(stream)

    rows = mg_rows("m1", carry_state=True)
    assert rows[-1].final and rows[-1].converged
    tok = rows[2]   # a V-cycle boundary
    resume = {"iters": tok.iters, "diff": tok.diff,
              "work_units": tok.work_units, "state": tok.state}
    resumed = mg_rows("m2", resume=resume)
    assert resumed[-1].final
    assert resumed[0].iters == tok.iters + 1   # cycles continue
    np.testing.assert_array_equal(resumed[-1].image, rows[-1].image)
    assert resumed[-1].iters == rows[-1].iters
    svc.close()


def test_service_resume_rejects_off_boundary_token():
    img = _img()
    svc = ConvolutionService(_mesh(), max_delay_s=0.002)
    bad = {"iters": 7, "diff": 1.0, "work_units": 7.0,
           "state": np.zeros((1,) + img.shape, np.float32)}
    r = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=0.0, max_iters=40, check_every=10, resume=bad)
    assert isinstance(r, Rejected) and r.reason == "invalid"
    assert "boundary" in r.detail
    wrong_shape = {"iters": 10, "diff": 1.0, "work_units": 10.0,
                   "state": np.zeros((1, 4, 4), np.float32)}
    r = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=0.0, max_iters=40, check_every=10, resume=wrong_shape)
    assert isinstance(r, Rejected) and r.reason == "invalid"
    svc.close()


def test_service_resume_accepts_final_partial_chunk_token():
    """max_iters that is NOT a check_every multiple: the last chunk is
    short and its token sits at iters == max_iters — a stream that died
    between that snapshot and the final row must still resume (the
    boundary check may not reject the one legitimate off-multiple
    boundary)."""
    img = _img(40, 56, seed=3)
    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    kw = dict(tol=0.0, max_iters=25, check_every=10)
    rows = _progressive_rows(svc, img, "fp1", carry_state=True, **kw)
    assert [r.iters for r in rows] == [10, 20, 25, 25]
    tok = rows[2]   # the short final chunk's snapshot (iters == 25)
    resume = {"iters": tok.iters, "diff": tok.diff,
              "work_units": tok.work_units, "state": tok.state}
    resumed = _progressive_rows(svc, img, "fp2", resume=resume, **kw)
    assert [r.iters for r in resumed] == [25] and resumed[-1].final
    np.testing.assert_array_equal(resumed[-1].image, rows[-1].image)
    svc.close()


def test_stream_rows_carry_state_only_when_asked():
    img = _img()
    svc = ConvolutionService(_mesh(), max_delay_s=0.002)
    stream = svc.submit_progressive(
        Request(image=img, filter_name="jacobi3", quantize=False),
        tol=0.0, max_iters=20, check_every=10, carry_state=True)
    rows = [encode_stream_row(r) for r in stream]
    assert all("state_b64" in r for r in rows if r["kind"] == "snapshot")
    assert "state_b64" not in rows[-1]          # finals never carry it
    tok = jobs.token_from_row(rows[0])
    assert tok is not None and tok["iters"] == 10
    np.testing.assert_array_equal(
        jobs.state_from_wire(tok["state_b64"], tok["state_shape"]).shape,
        (1,) + img.shape)
    svc.close()


# -------------------------------------------- router mid-stream resume


def _oracle_converge(img, body):
    r0 = ReplicaRouter([InProcessReplica(_factory((1, 2)), name="o0")],
                       start_health=False)
    st, rows = r0.converge(dict(body))
    out = list(rows)
    r0.close()
    assert out[-1]["kind"] == "final", out[-1]
    return out


def test_router_mid_stream_resume_chaos_disconnect():
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="ms1")
    want = _oracle_converge(img, body)
    router = _chaos_router(n=3)
    try:
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        assert status == 200
        final = got[-1]
        assert final["kind"] == "final", final
        assert sum(1 for g in got if g.get("kind") == "final") == 1
        assert final["image_b64"] == want[-1]["image_b64"]
        assert final["iters"] == want[-1]["iters"]
        # the resume is CLIENT-observable via the router stamp...
        assert final["router"]["resume_count"] == 1
        assert len(final["router"]["resumed_from"]) == 1
        # ...and OPERATOR-observable via /stats
        snap = router.snapshot()
        assert snap["router"]["resumes"] == 1
        assert snap["router"]["mid_stream_failovers"] == 1
        assert sum(p["resumes"]
                   for p in snap["replicas"].values()) == 1
        assert sum(p["mid_stream_failovers"]
                   for p in snap["replicas"].values()) == 1
        # the client never sees raw token state
        assert all("state_b64" not in g for g in got)
    finally:
        router.close()


def test_router_mid_stream_resume_on_replica_kill():
    """The acceptance drill in miniature: kill the serving replica AFTER
    rows have flowed; the job resumes on a survivor and the final row is
    byte-identical to the uninterrupted oracle run."""
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="k1")
    want = _oracle_converge(img, body)
    reps = [InProcessReplica(_factory((1, 2)), name=f"r{i}")
            for i in range(3)]
    router = ReplicaRouter(reps, poll_interval_s=0.05,
                           breaker_cooldown_s=0.2)
    try:
        status, rows = router.converge(dict(body))
        assert status == 200
        got = [next(rows)]
        serving = got[0]["router"]["replica"]
        router.replica(serving).kill()
        got.extend(rows)
        final = got[-1]
        assert final["kind"] == "final", final
        assert final["router"]["resume_count"] >= 1
        assert serving in final["router"]["resumed_from"]
        assert final["router"]["replica"] != serving
        assert final["image_b64"] == want[-1]["image_b64"]
        assert sum(1 for g in got if g.get("kind") == "final") == 1
    finally:
        router.close()


def test_router_client_retry_resumes_from_ledger():
    """All candidates dead mid-stream → typed retryable row; the client
    retry (same request_id) resumes from the router's ledger token
    instead of iteration 0."""
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="cr1")
    want = _oracle_converge(img, body)
    router = _chaos_router(n=1)
    try:
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        # rows flowed, then the only replica's stream died: typed end
        assert [g["kind"] for g in got[:-1]] == ["snapshot", "snapshot"]
        end = got[-1]
        assert end["kind"] == "rejected" and end["retryable"], end
        assert end.get("retry_after_s") is not None
        # the retry resumes: first row continues PAST the token
        status, rows = router.converge(dict(body))
        got2 = list(rows)
        assert got2[0]["iters"] == 30        # not 10 — resumed at 20
        final = got2[-1]
        assert final["kind"] == "final"
        assert final["router"]["resume_count"] == 1
        assert final["image_b64"] == want[-1]["image_b64"]
    finally:
        router.close()


def test_job_ledger_is_tenant_scoped():
    """request_id is client-stamped and route_key carries neither tenant
    nor image content: tenant B reusing tenant A's id on a same-config
    job must START FRESH, never be seeded from A's private field state —
    while A's own retry still resumes."""
    img = _img(40, 56, seed=3)
    router = _chaos_router(n=1)
    try:
        body_a = _converge_body(img, request_id="shared", tenant="A")
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body_a))
            got_a = list(rows)
        assert got_a[-1]["kind"] == "rejected"          # A died at 20
        body_b = _converge_body(img, request_id="shared", tenant="B")
        status, rows = router.converge(dict(body_b))
        got_b = list(rows)
        assert got_b[0]["iters"] == 10                  # B: iteration 0
        assert "resume_count" not in got_b[0].get("router", {})
        assert got_b[-1]["kind"] == "final"
        status, rows = router.converge(dict(body_a))    # A's own retry
        got_a2 = list(rows)
        assert got_a2[0]["iters"] == 30                 # resumed at 20
        assert got_a2[-1]["kind"] == "final"
    finally:
        router.close()


def test_multigrid_client_retry_resumes_from_ledger():
    """Multigrid tokens count V-cycles, not jacobi chunk boundaries —
    the router's token-fit guard must not apply the check_every rule to
    them (it would silently discard every multigrid ledger token and
    restart jobs from cycle 0 at full price)."""
    img = _img(48, 64, seed=3)
    body = _converge_body(img, request_id="mgr1", filter="blur3",
                          solver="multigrid", tol=1e-3, max_iters=400)
    router = _chaos_router(n=1)
    try:
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        assert [g.get("iters") for g in got[:-1]] == [1, 2]   # 2 cycles
        assert got[-1]["kind"] == "rejected" and got[-1]["retryable"]
        status, rows = router.converge(dict(body))            # retry
        got2 = list(rows)
        assert got2[0]["iters"] == 3, got2[0]    # resumed at cycle 2
        assert got2[0]["router"]["resume_count"] == 1
        assert got2[-1]["kind"] == "final" and got2[-1]["converged"]
    finally:
        router.close()


def test_raised_budget_retry_restarts_instead_of_invalid():
    """A token minted on the OLD budget's short final chunk no longer
    fits when the client retries with a bigger max_iters — the router
    must drop the unusable ledger token and restart the job, never fail
    it terminally 'invalid' on a token the client never supplied."""
    img = _img(40, 56, seed=3)
    router = _chaos_router(n=1)
    try:
        body = _converge_body(img, request_id="rb1", max_iters=45)
        with faults.injected("transport_stream:6"):   # die after the
            status, rows = router.converge(dict(body))  # iters=45 row
            got = list(rows)
        assert [g.get("iters") for g in got[:-1]] == [10, 20, 30, 40, 45]
        assert got[-1]["kind"] == "rejected" and got[-1]["retryable"]
        retry = dict(body, max_iters=100)
        status, rows = router.converge(retry)
        got2 = list(rows)
        assert got2[0].get("rejected") != "invalid", got2[0]
        assert got2[0]["iters"] == 10                 # fresh start
        assert got2[-1]["kind"] == "final"
        assert got2[-1]["iters"] == 100
    finally:
        router.close()


def test_router_incremental_charge_on_resume():
    """The r17 refund rule, extended: a resumed job's tenant charge
    covers only the incremental work.  Frozen quota clock → exact
    arithmetic: (full charge) − (refund of unexecuted fraction) +
    (retry's incremental charge) ≈ one full job's price."""
    from parallel_convolution_tpu.serving.pricing import WorkPricer

    clock = [0.0]
    quotas = TenantQuotas(rate=1.0, burst=1000.0,
                          clock=lambda: clock[0])
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="ic1", tenant="t1")
    # Floor lowered so this small job prices on the linear model (the
    # default 1e-4 floor would dominate and mask the arithmetic).
    pricer = WorkPricer(min_units=1e-9)
    router = _chaos_router(n=1, quotas=quotas, pricer=pricer)
    try:
        bucket = quotas.bucket("t1")
        level0 = bucket.level()
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        assert got[-1]["kind"] == "rejected"
        after_fail = bucket.level()
        # 20 of 40 iterations ran before the death: roughly half the
        # charge must have come back as the unexecuted-fraction refund.
        full = level0 - after_fail
        assert full > 0
        status, rows = router.converge(dict(body))
        got2 = list(rows)
        assert got2[-1]["kind"] == "final"
        total_charged = level0 - bucket.level()
        one_job = pricer.price(dict(body), converge=True)
        # net charge ≈ one uninterrupted job (the two legs' work sums
        # to the full budget; pricing is linear in max_iters)
        assert total_charged == pytest.approx(one_job, rel=0.15)
    finally:
        router.close()


def test_router_mid_stream_corrupt_counts_and_resumes():
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="cc1")
    want = _oracle_converge(img, body)
    router = _chaos_router(n=2, modes={"transport_stream": "corrupt"})
    try:
        with faults.injected("transport_stream:2"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        final = got[-1]
        assert final["kind"] == "final"
        assert final["image_b64"] == want[-1]["image_b64"]
        snap = router.snapshot()
        assert sum(p["corrupt_responses"]
                   for p in snap["replicas"].values()) == 1
    finally:
        router.close()


def test_truncate_frame_bytes_seed_sweep_always_bad_frame():
    """Detection isn't positional luck: a PCTE envelope cut short at
    ANY seeded depth must raise BadFrame, never decode clean (a clean
    decode would mean the framing has a length-check hole)."""
    img = _img(16, 24, seed=7)
    raw = frames.encode_envelope(
        {"kind": "snapshot", "iters": 10, "request_id": "t1"},
        {"image": np.ascontiguousarray(img)})
    for seed in range(96):
        cut = truncate_frame_bytes(raw, seed=seed)
        assert 0 < len(cut) < len(raw)
        with pytest.raises(frames.BadFrame):
            frames.decode_envelope(cut)
    # Degenerate inputs never produce a servable buffer either.
    assert truncate_frame_bytes(b"", seed=3) == b""
    assert truncate_frame_bytes(b"x", seed=3) == b""


def test_router_mid_stream_truncate_typed_retryable_then_resumes():
    """Satellite (b): a seeded mid-stream truncation of a converge
    envelope is a TYPED retryable end (never a hang, never garbage
    rows), and the client retry resumes from the ledger token instead
    of iteration 0."""
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="tr1")
    want = _oracle_converge(img, body)
    router = _chaos_router(n=1, modes={"transport_stream": "truncate"})
    try:
        with faults.injected("transport_stream:3"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        assert [g["kind"] for g in got[:-1]] == ["snapshot", "snapshot"]
        end = got[-1]
        assert end["kind"] == "rejected" and end["retryable"], end
        snap = router.snapshot()
        assert sum(p["corrupt_responses"]
                   for p in snap["replicas"].values()) == 1
        # the retry resumes: first row continues PAST the token
        status, rows = router.converge(dict(body))
        got2 = list(rows)
        assert got2[0]["iters"] == 30        # not 10 — resumed at 20
        final = got2[-1]
        assert final["kind"] == "final"
        assert final["router"]["resume_count"] == 1
        assert final["image_b64"] == want[-1]["image_b64"]
        assert sum(1 for g in got + got2
                   if g.get("kind") == "final") == 1
    finally:
        router.close()


def test_router_mid_stream_truncate_fails_over_byte_identical():
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="tr2")
    want = _oracle_converge(img, body)
    router = _chaos_router(n=2, modes={"transport_stream": "truncate"})
    try:
        with faults.injected("transport_stream:2"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        final = got[-1]
        assert final["kind"] == "final"
        assert final["router"]["resume_count"] == 1
        assert final["image_b64"] == want[-1]["image_b64"]
    finally:
        router.close()


class _ErrorStreamReplica:
    """A fake transport whose streams always die with a typed `error`
    row after one (token-carrying) snapshot — a DETERMINISTIC mid-
    stream execution failure every resume reproduces."""

    def __init__(self, name):
        self.name = name
        self.streams = 0

    def readyz(self):
        return 200, {"ok": True, "ready": True}

    def converge(self, body, timeout=None, traceparent=None):
        self.streams += 1
        b64, shape = jobs.state_to_wire(np.zeros((1, 8, 8), np.float32))
        rid = body.get("request_id", "")

        def rows():
            yield {"ok": True, "kind": "snapshot", "iters": 10,
                   "diff": 1.0, "work_units": 10.0, "solver": "jacobi",
                   "state_b64": b64, "state_shape": shape,
                   "request_id": rid}
            yield {"ok": False, "kind": "rejected", "rejected": "error",
                   "retryable": False, "detail": "deterministic boom",
                   "request_id": rid}

        return 200, rows()

    def close(self):
        pass


def test_deterministic_mid_stream_error_stays_non_retryable():
    """When the resume walk exhausts because a replica-typed `error`
    row reproduces on every candidate, the stream must end with THAT
    row verbatim (retryable:false) — reporting it as a retryable
    `replica_unavailable` would loop clients on a deterministic
    failure forever (the r14 taxonomy split, kept under durability)."""
    reps = [_ErrorStreamReplica(f"e{i}") for i in range(2)]
    router = ReplicaRouter(reps, start_health=False,
                           breaker_threshold=5)
    try:
        status, rows = router.converge(
            {"request_id": "det1", "max_iters": 40, "check_every": 10})
        got = list(rows)
        end = got[-1]
        assert end["kind"] == "rejected"
        assert end["rejected"] == "error", end
        assert end["retryable"] is False
        assert "deterministic boom" in end["detail"]
        # both candidates were tried (the walk DID attempt the resume)
        assert sum(r.streams for r in reps) == 2
        assert sum(1 for g in got if g.get("kind") == "final") == 0
    finally:
        router.close()


def test_non_durable_router_keeps_r14_semantics():
    """durable=False: a mid-stream death still ends the stream with the
    typed retryable row (no token traffic, no resume) — the r14
    contract is a flag away, not rewritten."""
    img = _img(40, 56, seed=3)
    body = _converge_body(img, request_id="nd1")
    router = _chaos_router(n=2, durable=False)
    try:
        with faults.injected("transport_stream:2"):
            status, rows = router.converge(dict(body))
            got = list(rows)
        assert got[0]["kind"] == "snapshot"
        assert got[-1]["kind"] == "rejected"
        assert got[-1]["rejected"] == "replica_unavailable"
        assert got[-1]["retryable"]
        assert router.stats["resumes"] == 0
        # non-durable converge asks for no token state on the wire
        assert all("state_b64" not in g for g in got)
    finally:
        router.close()
