"""Multigrid V-cycle + kernel-form registry (round 15).

Four proof surfaces:

1. REGISTRY PIN — the smoother key set matches the old ``backend ==``
   ladder exactly (no more, no less), transfer operators live under
   their own stencil forms, the overlap capability bit replaces the
   three per-call-site clamps, and unknown forms fail at resolution
   with the old ladder's error surface.
2. TRANSFER OPERATORS — full-weighting restriction and bilinear
   prolongation as sharded stencils vs INDEPENDENT NumPy loop formulas
   (both boundaries, odd/even extents, both centerings).
3. THE V-CYCLE — fixed point (a converged state doesn't move beyond
   tol; a periodic constant field is EXACT), work-units-to-tolerance
   ≥10× below plain Jacobi on the same seeded problem with the final
   states agreeing, bitwise mesh invariance, warm-cache compile
   flatness, and the solver knob threading (models/step/engine).
4. SERVING — progressive V-cycle rows (solver/work_units/mg_levels
   stamped), typed invalids for the multigrid float contract, and the
   serve-through-reshape drill: a converge job interrupted by the r10
   mesh ladder sheds typed-retryable, and completions are
   byte-identical across grids.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters
from parallel_convolution_tpu.parallel import kernels as kernel_forms
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.parallel import step as step_lib
from parallel_convolution_tpu.solvers import multigrid as mg
from parallel_convolution_tpu.solvers import transfer
from parallel_convolution_tpu.utils.config import (
    BACKENDS, BOUNDARIES, SOLVERS,
    VOLUME_PHYSICS_FORMS, VOLUME_SMOOTH_FORMS,
)
from parallel_convolution_tpu.utils.jax_compat import shard_map

JACOBI = filters.get_filter("jacobi3")


def _mesh(shape=(2, 2)):
    n = shape[0] * shape[1]
    return mesh_lib.make_grid_mesh(jax.devices()[:n], shape)


# ---------------------------------------------------------------- registry


def test_registry_smoother_keys_match_old_ladder_exactly():
    # The pinned migration proof: exactly the six historical rank-2
    # backends plus the four rank-3 volume smoothers (round 23), each
    # under exactly the two historical boundaries — no more, no less.
    want = frozenset((2, b, bd) for b in BACKENDS for bd in BOUNDARIES)
    want |= frozenset((3, n, bd) for n in VOLUME_SMOOTH_FORMS
                      for bd in BOUNDARIES)
    assert kernel_forms.registered_keys("smooth") == want


def test_registry_transfer_forms_registered_under_own_classes():
    assert kernel_forms.registered_keys("restrict") == frozenset(
        {(2, "restrict_fw", bd) for bd in BOUNDARIES}
        | {(3, "restrict_fw", bd) for bd in BOUNDARIES})
    assert kernel_forms.registered_keys("prolong") == frozenset(
        {(2, "prolong_bilinear", bd) for bd in BOUNDARIES}
        | {(3, "prolong_trilinear", bd) for bd in BOUNDARIES})
    # and the full set is the union: nothing else snuck in
    assert kernel_forms.registered_keys() == (
        kernel_forms.registered_keys("smooth")
        | kernel_forms.registered_keys("restrict")
        | kernel_forms.registered_keys("prolong")
        | kernel_forms.registered_keys("physics"))


def test_registry_rank3_physics_forms_pinned_exactly():
    # The time-dependent volume forms live under their own stencil
    # class — converge admission keys off "physics", not the name —
    # and the set is pinned exactly: wave + Gray-Scott, both
    # boundaries, nothing else, and no rank-2 physics.
    assert kernel_forms.registered_keys("physics") == frozenset(
        (3, n, bd) for n in VOLUME_PHYSICS_FORMS for bd in BOUNDARIES)


def test_registry_unknown_form_fails_at_resolution():
    with pytest.raises(ValueError, match="no kernel form registered"):
        kernel_forms.resolve(2, "no_such_backend", "zero")
    with pytest.raises(ValueError, match="boundary"):
        kernel_forms.resolve(2, "shifted", "moebius")
    with pytest.raises(ValueError, match="rank=3"):
        kernel_forms.resolve(3, "shifted", "zero")


def test_registry_conflicting_reregistration_raises():
    with pytest.raises(ValueError, match="already registered"):
        kernel_forms.register(kernel_forms.KernelForm(
            name="shifted", rank=2, stencil_form="smooth",
            boundaries=("zero", "periodic"), overlap_capable=True))


def test_overlap_capability_bit_is_the_one_clamp():
    # Only the RDMA form registered the overlapped pipeline; every other
    # smoother and both transfer operators inherit "not capable" — the
    # knowledge the three verbatim step.py clamps used to re-derive.
    for name in BACKENDS:
        want = name == "pallas_rdma"
        assert kernel_forms.overlap_capable(name) is want
        assert kernel_forms.clamp_overlap(True, name) is want
        assert kernel_forms.clamp_overlap(False, name) is False
    for name in ("restrict_fw", "prolong_bilinear", "unregistered"):
        assert kernel_forms.clamp_overlap(True, name) is False


def test_make_block_step_rejects_transfer_form_as_smoother():
    with pytest.raises(ValueError, match="restrict operator"):
        step_lib._make_block_step(
            JACOBI, (1, 1), (8, 8), (8, 8), False, "restrict_fw")


# ------------------------------------------------------- transfer operators


def _np_correlate3(x, taps, boundary):
    """Independent 3x3 correlation: explicit loops, ghost by boundary."""
    H, W = x.shape
    if boundary == "periodic":
        p = np.pad(x, 1, mode="wrap")
    else:
        p = np.pad(x, 1)
    out = np.zeros_like(x, np.float64)
    for di in range(3):
        for dj in range(3):
            out += taps[di, dj] * p[di:di + H, dj:dj + W]
    return out


FW_TAPS = np.array([[1, 2, 1], [2, 4, 2], [1, 2, 1]], np.float64) / 16.0


def _np_restrict(x, boundary):
    """Full weighting at the centering the boundary requires."""
    fw = _np_correlate3(x.astype(np.float64), FW_TAPS, boundary)
    off = 0 if boundary == "periodic" else 1
    ch = transfer.coarse_extent(x.shape[0], boundary)
    cw = transfer.coarse_extent(x.shape[1], boundary)
    return fw[off::2, off::2][:ch, :cw]


def _np_prolong(c, nh, nw, boundary):
    """Bilinear prolongation, explicit loops, ghost by boundary."""
    m, n = c.shape
    out = np.zeros((nh, nw))

    def cv(i, j):
        if boundary == "periodic":
            return c[i % m, j % n]
        if 0 <= i < m and 0 <= j < n:
            return c[i, j]
        return 0.0

    for fi in range(nh):
        for fj in range(nw):
            if boundary == "periodic":
                i2, r_i = divmod(fi, 2)
                j2, r_j = divmod(fj, 2)
                rows = [i2] if r_i == 0 else [i2, i2 + 1]
                cols = [j2] if r_j == 0 else [j2, j2 + 1]
            else:
                # odd-centered: fine 2k+1 = coarse k; fine 2k averages
                # coarse k-1, k (ghost 0 beyond the boundary)
                i2, r_i = divmod(fi - 1, 2)
                j2, r_j = divmod(fj - 1, 2)
                rows = [i2] if r_i == 0 else [i2, i2 + 1]
                cols = [j2] if r_j == 0 else [j2, j2 + 1]
            out[fi, fj] = np.mean(
                [np.mean([cv(i, j) for j in cols]) for i in rows])
    return out


def _sharded_op(form_name, x, grid, boundary, coarse_in=False):
    """Drive a registered transfer form through shard_map on ``grid``."""
    mesh = _mesh(grid)
    C, H, W = x.shape
    if coarse_in:
        # prolongation: input is the coarse field at half blocks of the
        # FINE geometry (vh, vw) carried alongside in x's metadata
        raise AssertionError("use _sharded_prolong")
    block = mg._level_block((H, W), grid, 2)
    xs = mg._fit_to(np.asarray(x, np.float32), (H, W), mesh, block,
                    src_mesh=None)
    build = kernel_forms.resolve(2, form_name, boundary).build
    fn = jax.jit(shard_map(build(grid, (H, W), block, boundary), mesh=mesh,
                           in_specs=mg._SPEC, out_specs=mg._SPEC,
                           check_vma=False))
    return np.asarray(fn(xs))


def _sharded_prolong(c, fine_hw, grid, boundary):
    mesh = _mesh(grid)
    H, W = fine_hw
    block = mg._level_block((H, W), grid, 2)
    half = (block[0] // 2, block[1] // 2)
    cs = mg._fit_to(np.asarray(c, np.float32)[None], c.shape, mesh, half,
                    src_mesh=None)
    build = kernel_forms.resolve(2, "prolong_bilinear", boundary).build
    fn = jax.jit(shard_map(build(grid, (H, W), block, boundary), mesh=mesh,
                           in_specs=mg._SPEC, out_specs=mg._SPEC,
                           check_vma=False))
    return np.asarray(fn(cs))[0, :H, :W]


@pytest.mark.parametrize("hw", [(12, 12), (13, 11), (16, 10), (15, 17)])
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)])
def test_restrict_fw_matches_numpy_zero(hw, grid):
    rng = np.random.default_rng(7)
    x = rng.standard_normal((1, *hw)).astype(np.float32)
    got = _sharded_op("restrict_fw", x, grid, "zero")
    ch = transfer.coarse_extent(hw[0], "zero")
    cw = transfer.coarse_extent(hw[1], "zero")
    want = _np_restrict(x[0], "zero")
    np.testing.assert_allclose(got[0, :ch, :cw], want, atol=1e-5)
    # the masking invariant: everything beyond the coarse extent is 0
    assert np.all(got[:, ch:, :] == 0) and np.all(got[:, :, cw:] == 0)


@pytest.mark.parametrize("hw", [(12, 16), (8, 12)])
def test_restrict_fw_matches_numpy_periodic(hw):
    rng = np.random.default_rng(8)
    x = rng.standard_normal((1, *hw)).astype(np.float32)
    got = _sharded_op("restrict_fw", x, (2, 2), "periodic")
    want = _np_restrict(x[0], "periodic")
    np.testing.assert_allclose(got[0], want, atol=1e-5)


@pytest.mark.parametrize("hw", [(12, 12), (13, 11), (16, 10)])
@pytest.mark.parametrize("grid", [(1, 1), (2, 2)])
def test_prolong_bilinear_matches_numpy_zero(hw, grid):
    rng = np.random.default_rng(9)
    ch = transfer.coarse_extent(hw[0], "zero")
    cw = transfer.coarse_extent(hw[1], "zero")
    c = rng.standard_normal((ch, cw)).astype(np.float32)
    got = _sharded_prolong(c, hw, grid, "zero")
    want = _np_prolong(c, *hw, "zero")
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("hw", [(12, 16), (8, 12)])
def test_prolong_bilinear_matches_numpy_periodic(hw):
    rng = np.random.default_rng(10)
    c = rng.standard_normal((hw[0] // 2, hw[1] // 2)).astype(np.float32)
    got = _sharded_prolong(c, hw, (2, 2), "periodic")
    want = _np_prolong(c, *hw, "periodic")
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_transfer_needs_even_blocks():
    with pytest.raises(ValueError, match="even per-device blocks"):
        transfer.build_restrict_fw((1, 1), (7, 8), (7, 8))
    with pytest.raises(ValueError, match="even per-device blocks"):
        transfer.build_prolong_bilinear((1, 1), (8, 7), (8, 7))


def test_coarse_extent_centering_rules():
    # zero: (n-1)//2 (odd-centered, inside); periodic: n//2 (wrap)
    assert [transfer.coarse_extent(n, "zero") for n in (8, 9, 12, 13)] == [
        3, 4, 5, 6]
    assert [transfer.coarse_extent(n, "periodic") for n in (8, 12)] == [4, 6]


# ----------------------------------------------------------- the V-cycle


def test_vcycle_fixed_point_periodic_constant_exact():
    # S preserves constants on a torus, the residual is identically 0,
    # restriction of 0 is 0 — one full cycle must return the EXACT field.
    c = np.full((1, 32, 32), 7.25, np.float32)
    out, res = mg.mg_converge(c, JACOBI, tol=1e-5, max_iters=500,
                              mesh=_mesh((2, 2)), boundary="periodic")
    assert res.converged and res.cycles == 1
    np.testing.assert_array_equal(out, c)


def test_vcycle_fixed_point_converged_state_barely_moves():
    tol = 1e-4
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 64, 48)).astype(np.float32)
    mesh = _mesh((2, 2))
    out, res = mg.mg_converge(x, JACOBI, tol=tol, max_iters=20000,
                              mesh=mesh)
    assert res.converged
    # one more cycle on the converged state: moves by O(tol), not more
    # (measured 1.8e-4 at tol=1e-4; 5x margin).  max_iters=1 work unit
    # admits exactly one cycle (the budget check precedes each cycle).
    rows = list(mg.mg_converge_stream(out, JACOBI, tol=0.0, max_iters=1,
                                      mesh=mesh))
    assert len(rows) == 1
    extra, _, residual, _ = rows[0]
    assert np.abs(extra - out).max() <= 5 * tol
    assert residual <= 5 * tol


def test_multigrid_beats_jacobi_10x_and_matches_oracle():
    # THE acceptance pin: same seeded problem, same stopping measure —
    # multigrid reaches tol in >=10x fewer fine-grid work units and the
    # two final states agree (measured: 26x, 8.2e-4 agreement).
    tol = 1e-4
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 64, 48)).astype(np.float32)
    mesh = _mesh((2, 2))
    out_mg, res = mg.mg_converge(x, JACOBI, tol=tol, max_iters=20000,
                                 mesh=mesh)
    out_j, iters = step_lib.sharded_converge(
        x, JACOBI, tol=tol, max_iters=20000, check_every=50, mesh=mesh,
        quantize=False)
    assert res.converged and iters < 20000
    assert iters / res.work_units >= 10.0
    assert np.abs(np.asarray(out_j, np.float32) - out_mg).max() <= 5e-3
    # work accounting sanity: cycles * per-cycle units, and > 1 level
    assert res.levels >= 2
    assert res.work_units == pytest.approx(
        res.cycles * mg.cycle_work_units(
            mg.plan_levels(mesh, (64, 48), 1, "zero")), abs=2e-3)


def test_multigrid_bitwise_mesh_invariant():
    # The r10 property the reshape drill leans on: the same problem on a
    # different grid produces byte-identical fields (the masking
    # invariant makes padding invisible; per-pixel op order is fixed).
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 64, 48)).astype(np.float32)
    out_a, res_a = mg.mg_converge(x, JACOBI, tol=1e-4, max_iters=20000,
                                  mesh=_mesh((2, 2)))
    out_b, res_b = mg.mg_converge(x, JACOBI, tol=1e-4, max_iters=20000,
                                  mesh=_mesh((1, 2)))
    assert res_a.cycles == res_b.cycles
    np.testing.assert_array_equal(out_a, out_b)


def test_multigrid_warm_cache_compiles_flat():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 48, 48)).astype(np.float32)
    mesh = _mesh((2, 2))
    out1, _ = mg.mg_converge(x, JACOBI, tol=1e-3, max_iters=5000,
                             mesh=mesh)
    misses = (mg._build_fine_smooth.cache_info().misses,
              mg._build_smooth_rhs.cache_info().misses,
              mg._build_residual_restrict.cache_info().misses,
              mg._build_prolong_correct.cache_info().misses)
    out2, _ = mg.mg_converge(x, JACOBI, tol=1e-3, max_iters=5000,
                             mesh=mesh)
    assert (mg._build_fine_smooth.cache_info().misses,
            mg._build_smooth_rhs.cache_info().misses,
            mg._build_residual_restrict.cache_info().misses,
            mg._build_prolong_correct.cache_info().misses) == misses
    np.testing.assert_array_equal(out1, out2)


def test_multigrid_float_contract_typed():
    x = np.zeros((1, 32, 32), np.float32)
    with pytest.raises(ValueError, match="quantize=False"):
        list(mg.mg_converge_stream(x, JACOBI, tol=1e-3, max_iters=10,
                                   mesh=_mesh((1, 1)), quantize=True))
    with pytest.raises(ValueError, match="storage='f32'"):
        list(mg.mg_converge_stream(x, JACOBI, tol=1e-3, max_iters=10,
                                   mesh=_mesh((1, 1)), storage="u8"))


def test_level_planner_respects_floor_and_cap():
    mesh = _mesh((2, 4))
    levels = mg.plan_levels(mesh, (96, 64), 1, "zero")
    assert levels[0].grid == (2, 4) and levels[0].valid_hw == (96, 64)
    for lv in levels:
        assert min(lv.block_hw) >= mg.MG_BLOCK_FLOOR  # the tile floor
    for lv in levels[:-1]:
        assert lv.block_hw[0] % 2 == 0 and lv.block_hw[1] % 2 == 0
    capped = mg.plan_levels(mesh, (96, 64), 1, "zero", mg_levels=2)
    assert len(capped) == 2
    with pytest.raises(ValueError, match="mg_levels"):
        mg.plan_levels(mesh, (96, 64), 1, "zero", mg_levels=0)


# -------------------------------------------------------- knob threading


def test_solver_knob_threads_models_and_step():
    from parallel_convolution_tpu.models import JacobiSolver

    rng = np.random.default_rng(3)
    x = rng.standard_normal((1, 48, 48)).astype(np.float32)
    mesh = _mesh((2, 2))
    s = JacobiSolver(filt="jacobi3", tol=1e-3, max_iters=5000, mesh=mesh,
                     quantize=False, solver="multigrid")
    out, cycles = s.solve(x)
    assert s.last_mg is not None and s.last_mg.cycles == cycles
    assert s.last_mg.converged
    # step-level dispatch produces the same bytes
    out2, cycles2 = step_lib.sharded_converge(
        x, JACOBI, tol=1e-3, max_iters=5000, mesh=mesh, quantize=False,
        solver="multigrid")
    assert cycles2 == cycles
    np.testing.assert_array_equal(out, out2)
    # and the stream twin yields one row per cycle, same final bytes
    rows = list(step_lib.sharded_converge_stream(
        x, JACOBI, tol=1e-3, max_iters=5000, mesh=mesh, quantize=False,
        solver="multigrid"))
    assert len(rows) == cycles
    np.testing.assert_array_equal(rows[-1][0], out)
    with pytest.raises(ValueError, match="solver"):
        step_lib.sharded_converge(x, JACOBI, tol=1e-3, max_iters=10,
                                  mesh=mesh, solver="sor")
    with pytest.raises(ValueError, match="solver"):
        JacobiSolver(solver="sor")
    assert set(SOLVERS) == {"jacobi", "multigrid"}


# --------------------------------------------------------------- serving


def _img(h=64, w=48, seed=5):
    return np.random.default_rng(seed).integers(
        0, 256, (h, w)).astype(np.uint8)


def test_serving_progressive_vcycle_rows():
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Rejected, Request, Snapshot,
    )

    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    try:
        img = _img()
        rows = list(svc.submit_progressive(
            Request(image=img, filter_name="jacobi3", quantize=False,
                    solver="multigrid"),
            tol=0.5, max_iters=5000, check_every=10))
        assert all(isinstance(r, Snapshot) for r in rows)
        assert rows[-1].final and rows[-1].converged
        # one row per V-cycle: iters counts cycles 1..N then the final
        assert [r.iters for r in rows[:-1]] == list(
            range(1, len(rows)))
        for r in rows:
            assert r.solver == "multigrid"
            assert r.mg_levels and r.mg_levels >= 2
            assert r.work_units > 0
        # residual trajectory reaches tol; work_units strictly increase
        assert rows[-1].diff < 0.5
        wus = [r.work_units for r in rows[:-1]]
        assert wus == sorted(wus) and len(set(wus)) == len(wus)

        # typed float-contract invalids at admission, not deep failures
        r = svc.submit_progressive(
            Request(image=img, solver="multigrid", quantize=True),
            tol=0.5, max_iters=10)
        assert isinstance(r, Rejected) and r.reason == "invalid"
        r = svc.submit_progressive(
            Request(image=img, solver="multigrid", quantize=False,
                    storage="u8"),
            tol=0.5, max_iters=10)
        assert isinstance(r, Rejected) and r.reason == "invalid"
        # the batch path is solver-less: multigrid sheds typed invalid
        r = svc.submit(Request(image=img, solver="multigrid",
                               quantize=False))
        assert isinstance(r, Rejected) and r.reason == "invalid"
        assert "converge" in r.detail
    finally:
        svc.close()


def test_serving_jacobi_rows_carry_solver_and_work_units():
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request,
    )

    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    try:
        rows = list(svc.submit_progressive(
            Request(image=_img(), filter_name="jacobi3", quantize=False),
            tol=0.05, max_iters=40, check_every=10))
        for r in rows:
            assert r.solver == "jacobi" and r.mg_levels is None
        # jacobi's fine-grid work units ARE its iterations
        assert [r.work_units for r in rows[:-1]] == [
            float(r.iters) for r in rows[:-1]]
    finally:
        svc.close()


def test_engine_key_solver_is_compile_identity():
    from parallel_convolution_tpu.serving.engine import WarmEngine

    eng = WarmEngine(mesh=_mesh((2, 2)))
    kw = dict(filter_name="jacobi3", storage="f32", iters=1, fuse=1,
              boundary="zero", quantize=False, backend="shifted")
    k_j = eng.key_for((1, 48, 48), **kw)
    k_m = eng.key_for((1, 48, 48), **kw, solver="multigrid")
    assert k_j != k_m and k_j.solver == "jacobi" and k_m.solver == "multigrid"
    k_m2 = eng.key_for((1, 48, 48), **kw, solver="multigrid", mg_levels=2)
    assert k_m2 != k_m  # the level cap changes the compiled schedule
    with pytest.raises(ValueError, match="solver"):
        dataclasses.replace(k_j, solver="sor").validate()


def test_mg_converge_stream_survives_reshape_with_typed_shed():
    # The serve-through-reshape drill: a multigrid converge job caught
    # by the r10 mesh ladder ends in a typed RETRYABLE shed (after its
    # best-so-far snapshots), the retry completes on the new grid, and
    # completions are byte-identical across grids.
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Rejected, Request, Snapshot,
    )

    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    try:
        img = _img()
        req = Request(image=img, filter_name="jacobi3", quantize=False,
                      solver="multigrid")
        # the uninterrupted run on the ORIGINAL grid = the byte oracle
        want = list(svc.submit_progressive(
            req, tol=0.5, max_iters=5000))[-1]
        assert isinstance(want, Snapshot) and want.final

        stream = iter(svc.submit_progressive(req, tol=0.5, max_iters=5000))
        first = next(stream)               # mid-flight: one cycle done
        assert isinstance(first, Snapshot) and first.solver == "multigrid"
        info = svc.reshape("1x2")          # the r10 ladder, mid-stream
        assert info["grid"] == (1, 2)
        tail = list(stream)
        assert tail, "interrupted stream must end with a typed row"
        shed = tail[-1]
        assert isinstance(shed, Rejected), shed
        assert shed.reason == "resharding" and shed.retryable
        # every pre-shed row was a valid best-so-far snapshot
        assert all(isinstance(r, Snapshot) for r in tail[:-1])

        # the retry lands on the NEW grid, byte-identical to the oracle
        rows = list(svc.submit_progressive(req, tol=0.5, max_iters=5000))
        final = rows[-1]
        assert isinstance(final, Snapshot) and final.final
        assert final.effective_grid == "1x2"
        assert final.iters == want.iters
        np.testing.assert_array_equal(final.image, want.image)
    finally:
        svc.close()


# ------------------------------------------------------ wire & bench rows


def test_frontend_stream_rows_carry_solver_fields():
    from parallel_convolution_tpu.serving.frontend import InProcessClient
    from parallel_convolution_tpu.serving.service import ConvolutionService
    from parallel_convolution_tpu.utils import imageio

    svc = ConvolutionService(_mesh((2, 2)), max_delay_s=0.002)
    try:
        import base64

        img = _img(48, 48, seed=6)
        body = {
            "image_b64": base64.b64encode(img.tobytes()).decode(),
            "rows": 48, "cols": 48, "mode": "grey",
            "filter": "jacobi3", "backend": "shifted",
            "tol": 0.5, "max_iters": 4000, "solver": "multigrid",
        }
        status, rows = InProcessClient(svc).converge(dict(body))
        rows = list(rows)
        assert status == 200
        assert rows[-1]["kind"] == "final" and rows[-1]["converged"]
        for r in rows:
            assert r["solver"] == "multigrid"
            assert r["work_units"] > 0 and r["mg_levels"] >= 2
        # decode round-trip keeps the oracle bytes honest
        got = np.frombuffer(base64.b64decode(rows[-1]["image_b64"]),
                            np.uint8).reshape(img.shape)
        x = imageio.interleaved_to_planar(img).astype(np.float32)
        want, _ = mg.mg_converge(x, JACOBI, tol=0.5, max_iters=4000,
                                 mesh=svc.engine.mesh)
        np.testing.assert_array_equal(
            got, np.clip(np.rint(want), 0, 255).astype(np.uint8)[0])
    finally:
        svc.close()


def test_bench_converge_rows_and_perf_gate_keying():
    from parallel_convolution_tpu.utils import bench

    mesh = _mesh((2, 2))
    row_j = bench.bench_converge((48, 48), JACOBI, tol=1e-3,
                                 max_iters=5000, mesh=mesh)
    row_m = bench.bench_converge((48, 48), JACOBI, tol=1e-3,
                                 max_iters=5000, mesh=mesh,
                                 solver="multigrid")
    assert row_j["solver"] == "jacobi" and row_j["mg_levels"] is None
    assert row_m["solver"] == "multigrid" and row_m["mg_levels"] >= 2
    assert row_j["converged"] and row_m["converged"]
    assert row_j["work_units_to_tol"] >= 10 * row_m["work_units_to_tol"]
    assert row_m["plan_key"].endswith("|solver=multigrid")
    # perf_gate separates the histories by solver — a multigrid row can
    # never be judged against the jacobi baseline for the same workload
    import importlib.util
    import sys
    from pathlib import Path

    scripts = Path(__file__).resolve().parent.parent / "scripts"
    spec = importlib.util.spec_from_file_location(
        "perf_gate", scripts / "perf_gate.py")
    perf_gate = importlib.util.module_from_spec(spec)
    sys.path.insert(0, str(scripts))  # perf_gate imports its _path shim
    try:
        spec.loader.exec_module(perf_gate)
    finally:
        sys.path.remove(str(scripts))
    assert perf_gate.row_key(row_j) != perf_gate.row_key(row_m)
    assert "solver=multigrid" in perf_gate.row_key(row_m)
    assert "solver=jacobi" in perf_gate.row_key(row_j)
