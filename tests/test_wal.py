"""Crash-safe control plane (round 19, ISSUE 15).

The acceptance properties, all on the 8-virtual-device CPU mesh:

* the WAL round-trips: every record appended is replayed into the SAME
  folded state by a fresh reader, across segment rotation (the fresh
  live file's compaction-snapshot head makes dropped generations
  lossless);
* replay is never silently partial (property-tested): random
  truncations of the live file replay a clean PREFIX (at most one torn
  tail record, reported); random byte flips anywhere else raise
  ``WALCorrupt`` with a typed cause; a damaged lineage QUARANTINES
  loudly and the router still boots;
* constructing a router over an existing WAL is a FENCED takeover: the
  epoch bumps past the WAL's and every replica's own fence, a converge
  stream interrupted by a router crash resumes from its newest durable
  token with a byte-identical final and exactly one final row per
  request_id ACROSS the restart, and the zombie predecessor's writes
  are rejected typed, non-retryable ``stale_epoch`` — including its
  own WAL appends (``WALFenced`` lineage check);
* the incremental-charge rule survives the restart: recovery refunds
  the interrupted job's unexecuted fraction (journaled, so a second
  recovery cannot refund twice) and the retry pays only the remainder;
* ``JobLedger`` capacity eviction skips PINNED (mid-stream) jobs and
  counts what it does evict (``ledger_evicted`` in ``/stats``);
* the DESIGN.md fault-site table matches ``faults.SITE_TABLE`` exactly
  (keys AND descriptions — the doc can never silently rot);
* the ``--static`` leg's lint actually detects what it claims to
  forbid (bare ``except:``, unlocked stats mutation under serving/).
"""

from __future__ import annotations

import base64
import json
import re
from pathlib import Path

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib
from parallel_convolution_tpu.resilience import degrade, faults
from parallel_convolution_tpu.serving.chaos import router_kill_due
from parallel_convolution_tpu.serving.jobs import JobLedger
from parallel_convolution_tpu.serving.pricing import WorkPricer
from parallel_convolution_tpu.serving.router import (
    InProcessReplica, ReplicaRouter, TenantQuotas,
)
from parallel_convolution_tpu.serving.service import ConvolutionService
from parallel_convolution_tpu.serving.wal import (
    RouterWAL, WALCorrupt, WALFenced, WALState, encode_record,
    parse_line, read_wal,
)
from parallel_convolution_tpu.utils import imageio

_TYPED_CAUSES = {"crc", "json", "format", "seq_gap", "unknown_kind"}


@pytest.fixture(autouse=True)
def _clean_global_state():
    yield
    faults.uninstall_plan()
    degrade.clear_probe_cache()


def _mesh(shape=(1, 2)):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _factory(shape=(1, 2), **kw):
    kw.setdefault("max_delay_s", 0.002)

    def make():
        return ConvolutionService(_mesh(shape), **kw)

    return make


def _img(rows=32, cols=48, seed=5):
    return imageio.generate_test_image(rows, cols, "grey", seed=seed)


def _converge_body(img, **kw):
    body = {"image_b64": base64.b64encode(
        np.ascontiguousarray(img).tobytes()).decode("ascii"),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "jacobi3", "backend": "shifted", "quantize": False,
        "tol": 0.0, "max_iters": 40, "check_every": 10}
    body.update(kw)
    return body


def _fill_wal(path, n_jobs=6, max_bytes=4096, fsync=False) -> RouterWAL:
    """A WAL with enough records to rotate at least once (tiny
    max_bytes), exercising the compaction-snapshot head."""
    w = RouterWAL(path, max_bytes=max_bytes, fsync=fsync)
    w.append("epoch", epoch=3)
    w.append("ring_add", name="r0")
    w.append("ring_add", name="r1")
    for i in range(n_jobs):
        lid = f"t\x1fjob{i}"
        w.append("admit", lid=lid, key=f"k{i}", cost=0.5, budget=40.0,
                 wu_start=0.0)
        w.append("token", lid=lid, key=f"k{i}", token={
            "iters": 10 * (i + 1), "diff": 0.5, "work_units":
            10.0 * (i + 1), "solver": "jacobi",
            # big enough that 6 tokens overflow the 4096-byte segment
            # floor — the fill must rotate at least once
            "state_b64": base64.b64encode(b"\x00" * 600).decode(),
            "state_shape": [1, 10, 15]})
        w.append("debt", tenant="t", delta=0.5, level=10.0 - 0.5 * i)
    w.append("final", lid="t\x1fjob0")
    w.append("ring_remove", name="r1")
    return w


# ------------------------------------------------------- codec + replay


def test_record_roundtrip_and_typed_parse_failures():
    rec = {"seq": 7, "kind": "epoch", "epoch": 4}
    line = encode_record(rec).rstrip("\n")
    assert parse_line(line) == rec
    with pytest.raises(ValueError, match="^format"):
        parse_line("nope")
    with pytest.raises(ValueError, match="^format"):
        parse_line("zzzzzzzz " + line[9:])
    # flip one payload byte: crc catches it
    bad = line[:-2] + ("X" if line[-2] != "X" else "Y") + line[-1]
    with pytest.raises(ValueError, match="^crc"):
        parse_line(bad)


def test_replay_matches_writer_state_across_rotation(tmp_path):
    p = tmp_path / "w.wal"
    w = _fill_wal(p)
    live_state = w.state.to_wire()
    w.close()
    # Rotation actually happened (tiny max_bytes) ...
    assert (tmp_path / "w.wal.1").exists()
    # ... and a fresh reader folds the identical state.
    records, torn = read_wal(p)
    assert torn is None
    st = WALState()
    for rec in records:
        st.apply(rec)
    assert st.to_wire() == live_state
    # seq strictly contiguous across the stitched generations
    seqs = [r["seq"] for r in records]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
    # the folded state saw the final: job0 gone, exactly-once mark kept
    assert "t\x1fjob0" not in st.jobs
    assert "t\x1fjob0" in st.finalized
    assert st.ring == {"r0"}
    assert st.ring_ever == {"r0", "r1"}


def test_reopen_is_takeover_rotation_and_fences_old_writer(tmp_path):
    p = tmp_path / "w.wal"
    w1 = _fill_wal(p)
    state1 = w1.state.to_wire()
    w2 = RouterWAL(p, fsync=False)
    assert w2.recovery_report["records"] > 0
    assert w2.state.to_wire() == state1
    # the takeover rotated the live file: the old writer is fenced
    with pytest.raises(WALFenced):
        w1.append("epoch", epoch=99)
    # and the new lineage still appends fine
    w2.append("epoch", epoch=4)
    assert w2.state.epoch == 4
    w1.close()
    w2.close()


# --------------------------------- never-a-silent-partial-replay property


def _pristine(tmp_path, name="w"):
    d = tmp_path / name
    d.mkdir()
    p = d / "w.wal"
    _fill_wal(p).close()
    records, torn = read_wal(p)
    assert torn is None
    return p, records


def test_truncation_property_prefix_or_torn_tail(tmp_path):
    """Random truncations of the LIVE file: replay always succeeds and
    always yields a clean PREFIX of the pristine record stream (the
    line containing the cut is the one tolerated torn tail)."""
    p, pristine = _pristine(tmp_path)
    data = p.read_bytes()
    rng = np.random.RandomState(0)
    for cut in sorted(rng.choice(len(data) - 1, size=12,
                                 replace=False)):
        p.write_bytes(data[:int(cut)])
        records, torn = read_wal(p)
        assert records == pristine[:len(records)], (
            f"cut@{cut}: replay is not a prefix")
        # nothing silently dropped: everything after the prefix is
        # explained by the cut (lines at/after the cut vanished whole,
        # plus at most one torn record reported)
        assert len(records) <= len(pristine)
    p.write_bytes(data)   # restore


def test_byte_flip_property_typed_corruption_or_torn_tail(tmp_path):
    """Random byte flips: damage in the newest file's LAST line is the
    tolerated torn tail (prefix replay); damage anywhere else raises
    WALCorrupt with a typed cause.  Never a silent partial replay."""
    p, pristine = _pristine(tmp_path, "flip")
    gen1 = p.with_name(p.name + ".1")
    rng = np.random.RandomState(1)
    for target in (p, gen1):
        data = target.read_bytes()
        last_line_start = data.rstrip(b"\n").rfind(b"\n") + 1
        for off in sorted(rng.choice(len(data) - 1, size=10,
                                     replace=False)):
            off = int(off)
            flipped = (data[:off] + bytes([data[off] ^ 0x55])
                       + data[off + 1:])
            target.write_bytes(flipped)
            try:
                records, torn = read_wal(p)
            except WALCorrupt as e:
                assert e.cause in _TYPED_CAUSES
            else:
                # Only legal on the newest file's last line.
                assert target == p and off >= last_line_start, (
                    f"flip@{target.name}:{off} replayed silently")
                assert torn is not None
                assert records == pristine[:len(records)]
                assert len(records) >= len(pristine) - 1
            finally:
                target.write_bytes(data)


def test_truncated_older_generation_is_corruption(tmp_path):
    """Cutting records out of a ROTATED generation is mid-log damage
    (its tail is not the live tail): typed quarantine, not tolerance."""
    p, _ = _pristine(tmp_path, "gen")
    gen1 = p.with_name(p.name + ".1")
    data = gen1.read_bytes()
    gen1.write_bytes(data[: len(data) // 2])
    with pytest.raises(WALCorrupt) as ei:
        read_wal(p)
    assert ei.value.cause in _TYPED_CAUSES


def test_closed_writer_cannot_reacquire_a_taken_over_lineage(tmp_path):
    """Review regression: the fencing identity is the OWNED inode, not
    the live fd — a writer that close()d (fh gone) used to reopen the
    successor's fresh live file and pass the vacuous fd-inode check,
    interleaving stale-seq records that quarantine the next replay."""
    p = tmp_path / "w.wal"
    w1 = RouterWAL(p, fsync=False)
    w1.append("epoch", epoch=1)
    w1.close()                      # fh gone; ownership remembered
    w2 = RouterWAL(p, fsync=False)  # the takeover rotation
    with pytest.raises(WALFenced):
        w1.append("debt", tenant="t", delta=1.0, level=2.0)
    w2.append("epoch", epoch=2)
    w2.close()
    # the lineage replays clean — no stale-seq pollution
    w3 = RouterWAL(p, fsync=False)
    assert w3.recovery_report["quarantined"] is None
    assert w3.state.epoch == 2
    w3.close()


def test_recovery_never_boots_an_empty_ring(tmp_path):
    """Review regression: ring replay removing EVERY provided replica
    (the pool is exactly the members the WAL saw scale-removed) must
    re-seat the pool loudly, not boot an unroutable router."""
    reps = [InProcessReplica(_factory(), name=f"g{i}") for i in range(2)]
    wal_path = tmp_path / "r.wal"
    r1 = ReplicaRouter(reps, wal=str(wal_path), start_health=False)
    r1.remove_replica("g1", drain_s=0.1, close=False)
    r1.close(close_replicas=False)
    with pytest.warns(RuntimeWarning, match="re-seating"):
        r2 = ReplicaRouter(reps[1:], wal=str(wal_path),
                           start_health=False)
    assert r2.ring.members() == ["g1"]
    r2.close(close_replicas=False)
    for r in reps:
        r.close()


def test_wal_state_job_cap_evicts_by_recency_not_admission_order():
    """Review regression: an active long-runner whose token records
    keep arriving must never be evicted from the WAL state's job cap
    ahead of older abandoned entries."""
    from parallel_convolution_tpu.serving import wal as wal_mod

    st = WALState()
    st.apply({"kind": "admit", "lid": "long", "key": "k",
              "cost": 1.0, "budget": 40.0, "wu_start": 0.0})
    for i in range(wal_mod._JOBS_CAP + 10):
        st.apply({"kind": "admit", "lid": f"idle{i}", "key": "k"})
        # the long-runner keeps streaming: every token is a touch
        st.apply({"kind": "token", "lid": "long", "key": "k",
                  "token": {"iters": i, "work_units": float(i)}})
    assert "long" in st.jobs
    assert st.jobs["long"]["cost"] == 1.0   # charge identity intact


def test_zombie_append_racing_takeover_never_corrupts(tmp_path):
    """Review regression (TOCTOU): a zombie appending in a tight loop
    while a successor takes over must either land its record BEFORE
    the rotation (still the legitimate writer) or fence — never
    interleave a stale-seq record into the rotated generation (which
    the next replay would quarantine as mid-log corruption)."""
    import threading

    p = tmp_path / "w.wal"
    for round_ in range(4):
        w = RouterWAL(p, fsync=False)
        w.append("epoch", epoch=round_ + 1)
        fenced = threading.Event()

        def hammer(wal=w):
            i = 0
            while not fenced.is_set() and i < 5000:
                i += 1
                try:
                    wal.append("debt", tenant="t", delta=1.0,
                               level=float(i))
                except WALFenced:
                    fenced.set()

        t = threading.Thread(target=hammer)
        t.start()
        w2 = RouterWAL(p, fsync=False)   # the racing takeover
        fenced.set()
        t.join()
        w2.close()
        w.close()
        # the lineage must replay clean after every racing takeover
        probe = RouterWAL(p, fsync=False)
        assert probe.recovery_report["quarantined"] is None, (
            f"round {round_}: {probe.recovery_report}")
        probe.close()


def test_debt_journal_is_atomic_with_the_balance():
    """Review regression: the WAL debt journal hook runs UNDER the
    bucket lock, so concurrent same-tenant charges/refunds record
    levels that chain exactly (level_k = level_{k-1} - delta_k with a
    frozen clock) — a level read outside the lock could journal a
    stale balance that recovery would re-mint."""
    import threading

    from parallel_convolution_tpu.serving.router import TokenBucket

    b = TokenBucket(rate=1.0, burst=1000.0, clock=lambda: 0.0)
    journal: list[tuple[float, float]] = []   # appended under b's lock

    def charge(n):
        for _ in range(200):
            b.try_take(n, journal=lambda lvl: journal.append((n, lvl)))

    threads = [threading.Thread(target=charge, args=(amt,))
               for amt in (0.5, 1.0, 1.5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    level = 1000.0
    for delta, recorded in journal:
        level -= delta
        assert recorded == pytest.approx(level), (
            "journaled level drifted from the op order")
    assert b.level() == pytest.approx(level)


def test_torn_tail_survives_two_restarts(tmp_path):
    """Review regression: the takeover rotation must AMPUTATE a
    tolerated torn tail before renaming the live file to ``.1`` —
    otherwise the next restart reads the torn bytes as MID-log
    corruption and quarantines state the compaction snapshot had
    perfectly preserved."""
    p = tmp_path / "w.wal"
    w = RouterWAL(p, fsync=False)
    w.append("epoch", epoch=1)
    w.append("ring_add", name="r0")
    w.close()
    data = p.read_bytes()
    p.write_bytes(data[:-7])   # tear the last record mid-line
    with pytest.warns(RuntimeWarning, match="torn tail"):
        w2 = RouterWAL(p, fsync=False)
    assert w2.recovery_report["torn_tail"] is not None
    assert w2.state.epoch == 1
    w2.append("debt", tenant="t", delta=1.0, level=2.0)
    w2.close()
    # restart #2: NO quarantine, nothing lost
    w3 = RouterWAL(p, fsync=False)
    assert w3.recovery_report["quarantined"] is None
    assert w3.state.epoch == 1
    assert w3.state.debts == {"t": 2.0}
    assert not list(tmp_path.glob("*.quarantined*"))
    w3.close()


def test_torn_only_wal_reopens_cleanly(tmp_path):
    """Review regression: a live file that is NOTHING but a torn line
    (zero surviving records) must still rotate at open — appending in
    'a' mode onto the stump used to merge the torn bytes with the new
    record and reset seq, corrupting the lineage for the NEXT reader."""
    p = tmp_path / "w.wal"
    p.write_text('deadbeef {"seq": 1, "kind": "epo')   # torn only
    with pytest.warns(RuntimeWarning, match="torn tail"):
        w = RouterWAL(p, fsync=False)
    assert w.recovery_report["records"] == 0
    w.append("epoch", epoch=5)
    w.append("ring_add", name="r0")
    w.close()
    w2 = RouterWAL(p, fsync=False)
    assert w2.recovery_report["quarantined"] is None
    assert w2.state.epoch == 5
    assert w2.state.ring == {"r0"}
    w2.close()


def test_quota_shed_and_settled_jobs_leave_no_recovery_refund(tmp_path):
    """Review regression: the admit record (charge identity) is
    journaled only AFTER quota admission, and every deliberate stream
    end settles it — recovery must never refund a charge that was
    never taken, or one already reconciled."""
    img = _img()
    reps = [InProcessReplica(_factory(), name="s0")]
    wal_path = tmp_path / "r.wal"
    quotas = TenantQuotas(rate=1e-9, burst=1e-9, clock=lambda: 0.0)
    # drain the bucket into debt first: a FULL tiny bucket would grant
    # a bigger-than-burst job via the r17 debt rule, not shed it
    assert quotas.take("poor", 1.0)[0]
    r1 = ReplicaRouter(reps, wal=str(wal_path), quotas=quotas,
                       pricer=WorkPricer(min_units=1e-9),
                       start_health=False)
    st, rows = r1.converge(_converge_body(img, request_id="shed-1",
                                          tenant="poor"))
    first = next(iter(rows))
    assert first["rejected"] == "tenant_quota"
    # no charge was taken -> no charge identity in the WAL
    assert all(j.get("cost") is None
               for j in r1.wal.state.jobs.values())
    # a COMPLETED job (final row) leaves no job entry at all
    st, rows = r1.converge(_converge_body(img, request_id="done-1",
                                          tenant="default"))
    assert list(rows)[-1]["kind"] == "final"
    assert "default\x1fdone-1" not in r1.wal.state.jobs
    r1.close(close_replicas=False)
    # recovery over this WAL refunds NOTHING
    r2 = ReplicaRouter(reps, wal=str(wal_path),
                       quotas=TenantQuotas(rate=1.0, burst=1e6,
                                           clock=lambda: 0.0),
                       pricer=WorkPricer(min_units=1e-9),
                       start_health=False)
    assert r2.recovery["refunded_jobs"] == {}
    r2.close(close_replicas=False)
    for r in reps:
        r.close()


def test_quarantine_moves_lineage_aside_and_starts_empty(tmp_path):
    p = tmp_path / "w.wal"
    _fill_wal(p).close()
    data = p.read_bytes()
    mid = len(data) // 3
    p.write_bytes(data[:mid] + bytes([data[mid] ^ 0xFF])
                  + data[mid + 1:])
    with pytest.warns(RuntimeWarning, match="quarantined"):
        w = RouterWAL(p, fsync=False)
    assert w.recovery_report["quarantined"] in _TYPED_CAUSES
    assert w.state.to_wire() == WALState().to_wire()
    assert list(tmp_path.glob("*.quarantined*"))
    # the fresh lineage is writable
    w.append("epoch", epoch=1)
    w.close()


# ------------------------------------------------ router recovery (e2e)


def _wal_router(reps, wal_path, clock=None, **kw):
    kw.setdefault("start_health", False)
    kw.setdefault("breaker_cooldown_s", 0.2)
    quotas = TenantQuotas(rate=1.0, burst=1e6,
                          clock=clock or (lambda: 0.0))
    return ReplicaRouter(reps, wal=str(wal_path), quotas=quotas,
                         pricer=WorkPricer(min_units=1e-9), **kw)


def test_router_crash_takeover_resume_exactly_once_and_zombie(tmp_path):
    """THE acceptance drill: kill the router mid-stream, take over the
    WAL, the client retry resumes byte-identically, exactly one final
    row per request_id across both lives, the zombie is fenced."""
    img = _img()
    reps = [InProcessReplica(_factory(), name=f"w{i}") for i in range(2)]
    # uninterrupted oracle
    clean = ReplicaRouter([InProcessReplica(_factory(), name="clean")],
                          start_health=False)
    _, rows = clean.converge(_converge_body(img, request_id="oracle"))
    oracle_final = list(rows)[-1]
    clean.close()
    assert oracle_final["kind"] == "final"

    wal_path = tmp_path / "r.wal"
    r1 = _wal_router(reps, wal_path)
    assert r1.epoch == 1
    finals = 0
    with faults.injected("router_kill:2"):
        st, rows = r1.converge(_converge_body(img, request_id="j1",
                                              tenant="t"))
        assert st == 200
        consumed = []
        for row in rows:
            consumed.append(row)
            finals += row.get("kind") == "final"
            if router_kill_due():
                break   # the crash: stream abandoned un-closed
    assert len(consumed) == 2 and finals == 0
    assert consumed[-1]["router"]["epoch"] == 1

    r2 = _wal_router(reps, wal_path)
    assert r2.epoch == 2
    assert r2.recovery["jobs_restored"] == 1
    # zombie: replica-side fence + WAL lineage fence
    stz, wz = r1.request({"image_b64": _converge_body(img)["image_b64"],
                          "rows": img.shape[0], "cols": img.shape[1],
                          "mode": "grey", "filter": "blur3", "iters": 1,
                          "request_id": "z", "tenant": "t"})
    assert stz == 409
    assert wz["rejected"] == "stale_epoch" and wz["retryable"] is False
    stz2, zrows = r1.converge(_converge_body(img, request_id="zc",
                                             tenant="t"))
    assert next(iter(zrows))["rejected"] == "stale_epoch"
    r1.close(close_replicas=False)

    st, rows = r2.converge(_converge_body(img, request_id="j1",
                                          tenant="t"))
    got = list(rows)
    final = got[-1]
    assert final["kind"] == "final"
    # resumed, not restarted: first retry row continues past the crash
    assert got[0]["iters"] > consumed[-1]["iters"]
    assert final["router"]["resume_count"] >= 1
    assert final["router"]["epoch"] == 2
    assert final["image_b64"] == oracle_final["image_b64"]
    finals += sum(r.get("kind") == "final" for r in got)
    assert finals == 1
    r2.close(close_replicas=False)
    for r in reps:
        r.close()


def test_incremental_charge_across_restart(tmp_path):
    """Recovery refunds the interrupted job's unexecuted fraction (and
    journals the consumption), so die-takeover-resume-complete costs
    one uninterrupted job under a frozen clock — and a THIRD recovery
    of the same WAL refunds nothing more."""
    img = _img()
    reps = [InProcessReplica(_factory(), name=f"q{i}") for i in range(2)]
    wal_path = tmp_path / "r.wal"
    r1 = _wal_router(reps, wal_path)
    one_job = WorkPricer(min_units=1e-9).price(
        _converge_body(img), converge=True)
    level0 = r1.quotas.bucket("t").level()
    with faults.injected("router_kill:2"):
        st, rows = r1.converge(_converge_body(img, request_id="c1",
                                              tenant="t"))
        for row in rows:
            if router_kill_due():
                break
    r1.close(close_replicas=False)

    r2 = _wal_router(reps, wal_path)
    assert r2.recovery["refunded_jobs"], "no recovery refund recorded"
    st, rows = r2.converge(_converge_body(img, request_id="c1",
                                          tenant="t"))
    assert list(rows)[-1]["kind"] == "final"
    charged = level0 - r2.quotas.bucket("t").level()
    assert charged == pytest.approx(one_job, rel=0.15)
    r2.close(close_replicas=False)
    # a third life must NOT refund the consumed charge again
    r3 = _wal_router(reps, wal_path)
    assert not r3.recovery["refunded_jobs"]
    r3.close(close_replicas=False)
    for r in reps:
        r.close()


def test_ring_membership_replays_across_restart(tmp_path):
    reps = [InProcessReplica(_factory(), name=f"m{i}") for i in range(3)]
    wal_path = tmp_path / "r.wal"
    r1 = ReplicaRouter(reps, wal=str(wal_path), start_health=False)
    r1.remove_replica("m2", drain_s=0.1, close=False)
    assert r1.ring.members() == ["m0", "m1"]
    r1.close(close_replicas=False)
    # same pool provided again: the WAL remembers m2 left
    r2 = ReplicaRouter(reps, wal=str(wal_path), start_health=False)
    assert r2.ring.members() == ["m0", "m1"]
    assert "m2" in r2.recovery["ring_removed"]
    # a recovered member with NO transport is dropped loudly
    r2.close(close_replicas=False)
    with pytest.warns(RuntimeWarning, match="no transport"):
        r3 = ReplicaRouter(reps[:1], wal=str(wal_path),
                           start_health=False)
    assert r3.ring.members() == ["m0"]
    assert "m1" in r3.recovery["dropped_members"]
    r3.close(close_replicas=False)
    for r in reps:
        r.close()


def test_epoch_reconciles_past_replica_fences(tmp_path):
    """Even with the WAL lost/quarantined, the new epoch lands above
    every replica's own fence — a zombie cannot win via WAL loss."""
    reps = [InProcessReplica(_factory(), name="f0")]
    reps[0].service.fence(7)
    r = ReplicaRouter(reps, wal=str(tmp_path / "fresh.wal"),
                      start_health=False)
    assert r.epoch == 8
    assert r.recovery["max_replica_fence"] == 7
    r.close()


def test_wal_append_failure_degrades_durability_not_serving(tmp_path):
    img = _img()
    reps = [InProcessReplica(_factory(), name="d0")]
    r = _wal_router(reps, tmp_path / "r.wal")
    with faults.injected("wal_write:1+"):
        st, rows = r.converge(_converge_body(img, request_id="d1",
                                             tenant="t"))
        got = list(rows)
    assert got[-1]["kind"] == "final"
    assert r.stats["wal_write_errors"] > 0
    r.close()


def test_epoch_stamped_on_batch_responses(tmp_path):
    img = _img()
    reps = [InProcessReplica(_factory(), name="e0")]
    r = _wal_router(reps, tmp_path / "r.wal")
    st, wire = r.request({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(img).tobytes()).decode(),
        "rows": img.shape[0], "cols": img.shape[1], "mode": "grey",
        "filter": "blur3", "iters": 1, "request_id": "e", "tenant": "t"})
    assert wire["ok"] and wire["router"]["epoch"] == r.epoch
    want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 1)
    got = np.frombuffer(base64.b64decode(wire["image_b64"]),
                        np.uint8).reshape(img.shape)
    assert np.array_equal(got, want)
    r.close()


# ------------------------------------------------ service-side fencing


def test_epoch_gate_ratchets_and_rejects():
    svc = ConvolutionService(_mesh(), start=False)
    ok, cur = svc.epoch_gate(None)
    assert ok and cur == 0
    ok, cur = svc.epoch_gate(3)
    assert ok and cur == 3
    ok, cur = svc.epoch_gate(3)          # equal epoch stays admitted
    assert ok
    ok, cur = svc.epoch_gate(2)          # stale: rejected, fence kept
    assert not ok and cur == 3
    assert svc.stats["rejected_stale_epoch"] == 1
    assert svc.fence(10) == 10
    assert svc.fence(4) == 10            # never lowers
    assert svc.snapshot()["fence_epoch"] == 10
    assert svc.readiness()[1]["fence_epoch"] == 10
    svc.close()


def test_router_kill_due_consults_the_seeded_plan():
    with faults.injected("router_kill:3"):
        assert [router_kill_due() for _ in range(4)] == [
            False, False, True, False]


# ------------------------------------------------ ledger eviction fix


def test_ledger_eviction_skips_pinned_jobs_and_counts():
    """Regression (ISSUE 15 satellite): a capacity-evicted MID-STREAM
    job used to silently lose its resume token."""
    led = JobLedger(capacity=3)
    row = {"ok": True, "iters": 10, "work_units": 10.0,
           "state_b64": "AA==", "state_shape": [1, 1, 1]}
    led.observe("live", "k", dict(row))
    led.pin("live")
    for i in range(6):
        led.observe(f"idle{i}", "k", dict(row))
    # the pinned mid-stream job survived the churn ...
    assert led.token("live", "k") is not None
    # ... idle entries were the victims, and the counter says so
    snap = led.snapshot()
    assert snap["ledger_evicted"] == 4
    assert snap["pinned"] == 1
    led.unpin("live")
    # unpinned, it becomes ordinary FIFO prey again
    for i in range(6, 10):
        led.observe(f"idle{i}", "k", dict(row))
    assert led.token("live", "k") is None
    # soft bound: all-pinned overflow never evicts a live job
    led2 = JobLedger(capacity=2)
    for i in range(4):
        rid = f"p{i}"
        led2.observe(rid, "k", dict(row))
        led2.pin(rid)
    assert len(led2) == 4
    assert all(led2.token(f"p{i}", "k") is not None for i in range(4))


def test_ledger_restore_rebounds_and_keeps_finalized():
    led = JobLedger(capacity=2)
    jobs = {f"j{i}": {"key": "k", "token": {"iters": i},
                      "resume_count": i, "resumed_from": ["a"] * i}
            for i in range(4)}
    led.restore(jobs, finalized=["done1", "done2"])
    assert len(led) == 2               # re-bounded to capacity
    assert led.finalize("done1") is False   # exactly-once survives
    assert led.finalize("fresh") is True


# ------------------------------------- DESIGN.md site-table drift guard


def test_design_fault_site_table_matches_code_exactly():
    """The DESIGN.md fault-site table (between the HTML markers) is
    faults.SITE_TABLE verbatim — keys AND descriptions."""
    design = (Path(faults.__file__).resolve().parents[2]
              / "DESIGN.md").read_text()
    m = re.search(r"<!-- fault-site-table:begin -->\n(.*?)"
                  r"<!-- fault-site-table:end -->", design, re.S)
    assert m, "fault-site table markers missing from DESIGN.md"
    documented = {}
    for line in m.group(1).splitlines():
        row = re.match(r"\|\s*`([a-z_]+)`\s*\|\s*(.*?)\s*\|\s*$", line)
        if row:
            documented[row.group(1)] = row.group(2)
    code = {site: " ".join(desc.split())
            for site, desc in faults.SITE_TABLE.items()}
    assert documented == code, (
        "DESIGN.md fault-site table drifted from faults.SITE_TABLE: "
        f"doc-only {sorted(set(documented) - set(code))}, "
        f"code-only {sorted(set(code) - set(documented))}, "
        f"description diffs "
        f"{[k for k in set(code) & set(documented) if code[k] != documented[k]]}")


# --------------------------------------------- the --static leg's lint


def test_static_lint_detects_what_it_forbids(tmp_path):
    import importlib.util as ilu

    spec = ilu.spec_from_file_location(
        "static_check", Path(faults.__file__).resolve().parents[2]
        / "scripts" / "static_check.py")
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)

    bad = tmp_path / "serving" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(
        "class S:\n"
        "    def f(self):\n"
        "        try:\n"
        "            pass\n"
        "        except:\n"
        "            pass\n"
        "        self.stats['x'] += 1\n"
        "    def g(self):\n"
        "        with self._lock:\n"
        "            self.stats['x'] += 1\n")
    assert len(mod.check_bare_except([bad])) == 1
    lock_problems = mod.check_stats_locking([bad])
    assert len(lock_problems) == 1 and ":7:" in lock_problems[0]
    # and the real serving/ tree passes both
    serving = [p for p in mod.py_files() if "serving" in p.parts]
    assert mod.check_stats_locking(serving) == []
    assert mod.check_bare_except(mod.py_files()) == []


def test_wal_records_are_wire_shaped():
    """Every record kind the router writes must JSON-roundtrip through
    the codec (torn-tail classification depends on per-line parse)."""
    st = WALState()
    for i, (kind, fields) in enumerate([
            ("epoch", {"epoch": 2}),
            ("admit", {"lid": "t\x1fa", "key": "k", "cost": 0.5,
                       "budget": 40.0, "wu_start": 0.0}),
            ("token", {"lid": "t\x1fa", "key": "k",
                       "token": {"iters": 10, "work_units": 10.0}}),
            ("resume", {"lid": "t\x1fa", "key": "k",
                        "from_replica": "r0"}),
            ("job_settled", {"lid": "t\x1fa"}),
            ("final", {"lid": "t\x1fa"}),
            ("ring_add", {"name": "r0"}),
            ("ring_remove", {"name": "r0"}),
            ("debt", {"tenant": "t", "delta": 1.0, "level": 3.0}),
            ("snapshot", {"state": WALState().to_wire()})]):
        rec = {"seq": i + 1, "kind": kind, **fields}
        assert parse_line(encode_record(rec).rstrip("\n")) == rec
        st.apply(rec)


# -- round 21: sharded control plane — per-shard lineages, per-shard
# -- fencing, multi-lineage isolation --------------------------------------

def test_wal_refuses_numeric_suffix_lineage_name(tmp_path):
    """Rotation names generations ``<name>.1``, ``<name>.2``, ... — a
    lineage whose own name ends in ``.<digits>`` would be read as a
    sibling's rotated generation.  The constructor refuses it."""
    with pytest.raises(ValueError, match="collides"):
        RouterWAL(tmp_path / "ctl.wal.2")
    # Non-numeric suffixes (the shard naming convention) are fine.
    RouterWAL(tmp_path / "shard-2.wal").close()


def test_quarantine_renames_never_clobber(tmp_path):
    """A second quarantine of the same lineage must not overwrite the
    first one's forensic evidence (unique ``.quarantined.N`` names)."""
    path = tmp_path / "ctl.wal"
    for round_no in (1, 2):
        w = RouterWAL(path, fsync=False)
        for i in range(3):
            w.append("ring_add", name=f"r{round_no}{i}")
        w.close()
        # MID-log damage (first line of several — a damaged ONLY line
        # would be tolerated as a torn tail, not quarantined).
        raw = bytearray(path.read_bytes())
        raw[5] ^= 0xFF
        path.write_bytes(bytes(raw))
        with pytest.warns(RuntimeWarning, match="quarantined"):
            w2 = RouterWAL(path, fsync=False)
        assert w2.recovery_report["quarantined"] in _TYPED_CAUSES
        w2.close()
    quarantined = sorted(p.name for p in tmp_path.iterdir()
                         if ".quarantined" in p.name)
    # Two rounds of damage → at least two distinct quarantine names
    # (never an os.replace clobber of the first round's evidence).
    assert len(quarantined) >= 2, quarantined


def test_multi_lineage_quarantine_isolation(tmp_path):
    """Corrupting shard A's lineage quarantines A's files ONLY —
    shard B, sharing the directory, replays untouched."""
    a_path = tmp_path / "shard-a.wal"
    b_path = tmp_path / "shard-b.wal"
    wa = RouterWAL(a_path, shard="a", fsync=False)
    wb = RouterWAL(b_path, shard="b", fsync=False)
    for i in range(4):
        wa.append("ring_add", name=f"ra{i}")
        wb.append("ring_add", name=f"rb{i}")
    wa.close()
    wb.close()
    raw = bytearray(a_path.read_bytes())
    raw[5] ^= 0xFF   # mid-log damage in A
    a_path.write_bytes(bytes(raw))
    with pytest.warns(RuntimeWarning, match="quarantined"):
        wa2 = RouterWAL(a_path, shard="a", fsync=False)
    assert wa2.recovery_report["quarantined"] is not None
    wa2.close()
    wb2 = RouterWAL(b_path, shard="b", fsync=False)
    assert wb2.recovery_report["quarantined"] is None
    assert wb2.state.ring == {f"rb{i}" for i in range(4)}
    wb2.close()
    # no B file was renamed aside
    assert not [p.name for p in tmp_path.iterdir()
                if p.name.startswith("shard-b")
                and ".quarantined" in p.name]


def test_shard_stamp_and_crossed_lineage_refused(tmp_path):
    """Every record a sharded writer appends carries its shard label;
    replaying a lineage stamped for a DIFFERENT shard is typed
    corruption (crossed files), never a silent splice."""
    path = tmp_path / "shard-a.wal"
    w = RouterWAL(path, shard="a", fsync=False)
    rec = w.append("ring_add", name="r0")
    assert rec["shard"] == "a"
    w.close()
    records, _torn = read_wal(path)
    assert records and all(r.get("shard") == "a" for r in records)
    # same file adopted under the WRONG shard label → quarantine
    with pytest.warns(RuntimeWarning, match="quarantined"):
        wrong = RouterWAL(path, shard="b", fsync=False)
    assert wrong.recovery_report["quarantined"] == "format"
    wrong.close()
    # an UNSHARDED reader (legacy) adopts shard-stamped records fine —
    # and a sharded reader adopts unstamped legacy records fine.
    legacy_path = tmp_path / "legacy.wal"
    lw = RouterWAL(legacy_path, fsync=False)
    lw.append("ring_add", name="r1")
    lw.close()
    adopted = RouterWAL(legacy_path, shard="c", fsync=False)
    assert adopted.recovery_report["quarantined"] is None
    assert adopted.state.ring == {"r1"}
    adopted.close()


def test_per_shard_fencing_zombie_on_a_live_on_b(tmp_path):
    """Per-SHARD, not per-process, fencing: after shard A's lineage is
    taken over, the old owner is a zombie FOR SHARD A ONLY — the same
    process's ownership of shard B keeps serving."""
    img = _img()
    rep = InProcessReplica(_factory(), name="w0")
    ra = _wal_router([rep], tmp_path / "shard-a.wal", shard="a")
    rb = _wal_router([rep], tmp_path / "shard-b.wal", shard="b")
    assert ra.epoch == 1 and rb.epoch == 1
    # takeover of A by a NEW router (same replica pool)
    ra2 = _wal_router([rep], tmp_path / "shard-a.wal", shard="a")
    assert ra2.epoch == 2
    # zombie on A: typed stale_epoch, non-retryable, scoped to shard a
    status, wire = ra.request(
        dict(_converge_body(img), filter="blur3", request_id="za"))
    assert status == 409 and wire["rejected"] == "stale_epoch"
    assert wire.get("shard") == "a"
    # ...but the SAME process's shard-B ownership still serves.
    status, wire = rb.request(
        dict(_converge_body(img), filter="blur3", request_id="zb"))
    assert status == 200 and wire["ok"], wire
    assert wire["router"]["shard"] == "b"
    # and the replica reports both ratchets independently.
    fences = rep.snapshot().get("fence_epochs", {})
    assert fences.get("a") == 2 and fences.get("b") == 1
    for r in (ra, ra2, rb):
        r.close(close_replicas=False)
    rep.close()


def test_shard_router_takeover_and_fleet_quota(tmp_path):
    """The peer layer end-to-end, in process: boot 3 single-shard
    routers, kill one, a surviving peer performs the fenced takeover
    of the orphaned lineage (deterministic successor), the client's
    map refresh makes the move invisible, and tenant debt replicates
    so fleet-wide admitted cost never exceeds one router's budget."""
    from parallel_convolution_tpu.serving.peers import (
        InProcessPeer, ShardClient, ShardRouter, shard_of,
    )
    from parallel_convolution_tpu.serving.router import route_key

    img = _img()
    reps = [InProcessReplica(_factory(), name=f"w{i}") for i in range(2)]
    names = ["rA", "rB", "rC"]
    assign = {"0": "rA", "1": "rB", "2": "rC"}
    # ONE shared quota pool per router process (here: one per router,
    # replicated via the debt log), frozen clock = no refill.
    quotas = {nm: TenantQuotas(rate=1.0, burst=4.0,
                               clock=lambda: 0.0) for nm in names}
    routers = {}
    for nm in names:
        routers[nm] = ShardRouter(
            nm, reps, n_shards=3,
            owned=[s for s, o in assign.items() if o == nm],
            state_dir=tmp_path, assignments=assign,
            quotas=quotas[nm], pricer=WorkPricer(min_units=1e-9),
            start_sync=False, start_health=False,
            breaker_cooldown_s=0.2, clock=lambda: 0.0)
    for nm in names:
        routers[nm].peers = [InProcessPeer(routers[o])
                             for o in names if o != nm]
    client = ShardClient(list(routers.values()))

    body = _converge_body(img, request_id="job-1", tenant="t1")
    shard = shard_of(route_key(dict(body)), 3)
    victim_name = assign[shard]
    victim = routers[victim_name]
    survivors = [routers[n] for n in names if n != victim_name]

    # mid-stream kill: consume two rows, then SIGKILL-equivalent.
    status, rows = client.converge(dict(body))
    assert status == 200
    consumed = [next(rows), next(rows)]
    assert consumed[-1]["router"]["shard"] == shard
    victim.hard_stop()
    # survivors notice via anti-entropy and take over deterministically
    for r in survivors:
        for _ in range(r.suspect_after + 1):
            r.sync_now()
    owners = [r for r in survivors if shard in r._sub]
    assert len(owners) == 1, [r.name for r in survivors]
    successor = owners[0]
    assert successor.stats["takeovers"] == 1
    assert successor.sub(shard).epoch == victim.sub(shard).epoch + 1
    # zombie write on the taken-over shard: typed stale_epoch
    _zst, z_rows = victim.sub(shard).converge(
        dict(body, request_id="zombie-1"))
    assert next(iter(z_rows))["rejected"] == "stale_epoch"
    # the client retry resumes byte-identically, exactly one final
    client.refresh()
    status, rows2 = client.converge(dict(body))
    got = list(rows2)
    final = got[-1]
    assert final["kind"] == "final"
    assert final["router"]["resume_count"] >= 1
    assert final["router"]["shard"] == shard
    assert final["iters"] > consumed[-1]["iters"]
    clean = ReplicaRouter([InProcessReplica(_factory(), name="clean")],
                          start_health=False)
    _, orows = clean.converge(_converge_body(img, request_id="oracle"))
    oracle_final = list(orows)[-1]
    clean.close()
    assert final["image_b64"] == oracle_final["image_b64"]
    # fleet-wide quota: tenant t2 is charged on rB; after peer sync,
    # rC's local bucket reflects the charge, so total admitted cost
    # across the fleet never exceeds one router's budget.
    lvl_before = quotas["rC"].bucket("t2").level()
    quotas["rB"].take("t2", 3.0)
    routers["rB"].debts.record("t2", 3.0)
    for r in survivors:
        r.sync_now()
    lvl_after = quotas["rC"].bucket("t2").level()
    assert lvl_after <= lvl_before - 3.0 + 1e-9
    for r in routers.values():
        try:
            r.close(close_replicas=False)
        except Exception:
            pass
    for rep in reps:
        rep.close()
