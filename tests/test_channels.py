"""Persistent, partitioned halo channels + the packed/strided column A/B.

Round 16's three claims, each pinned here:

* **Persistent channels** (``parallel.channels``): descriptor plans are
  bound ONCE per exchange identity and reused by every trace that
  shares it — fused iteration chunks, converge chunks, V-cycle levels —
  with the build/hit counters as assertable evidence, and the 1x1
  grid's plan holding NO channels at all (the static-elision contract:
  the degenerate program is the serialized local program verbatim,
  independent of ``col_mode``/``partitioned``, pinned at the LOWERED
  PROGRAM level).

* **Partitioned completion**: a region/window waits on exactly the slab
  channels whose inbound write rectangle its read region overlaps — no
  missed wait (a race), no extra wait (lost overlap).  The wait-set
  derivations the kernels consume (``overlap_region_slabs``,
  ``tiled_window_hazards``) are property-tested against independent
  interval intersection over the ISSUE's grid/boundary/fuse matrix,
  and full-protocol byte proofs run under the DMA-faithful interpreter
  (skip-with-cause on stock jax, like tests/test_rdma.py).

* **Packed-vs-strided column transport**: both modes byte-identical
  through kernels and dispatch, the cost model's split setup/transfer
  exchange terms and the new constants drift-guarded, and the resolved
  ``col_mode`` threaded plan→search→bench rows→EngineKey→responses.
"""

import numpy as np
import pytest

import jax

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import (
    channels, kernels as kernel_forms, mesh as mesh_lib, step,
)
from parallel_convolution_tpu.utils import imageio, jax_compat

needs_faithful_interpret = pytest.mark.skipif(
    not jax_compat.HAS_TPU_INTERPRET,
    reason="DMA-faithful TPU interpret mode unavailable in this jax "
           "(needs current jax, or real silicon)")


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]],
                                   shape)


def _run(img, filt, iters, mesh_shape, *, boundary="zero", fuse=1,
         overlap=False, col_mode="strided", partitioned=True,
         tiled=None, tile=None):
    """Chained fused_rdma_step invocations straight at the kernel (the
    dispatch clamps deliberately bypassed: this file proves PROGRAM
    bytes per (col_mode, partitioned, overlap) variant)."""
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    mesh = _mesh(mesh_shape)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    valid_hw = None if boundary == "periodic" else img.shape[:2]
    n = iters // fuse

    def body(v):
        import jax.lax as lax

        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, mesh_shape, boundary, quantize=True,
                tiled=tiled, tile=tile, fuse=fuse, valid_hw=valid_hw,
                overlap=overlap, col_mode=col_mode,
                partitioned=partitioned)
        return lax.fori_loop(0, n, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    return np.asarray(out)[0].astype(np.uint8)


# ---------------------------------------------------------------------------
# The channel-plan layer: identity, caching, static elision.
# ---------------------------------------------------------------------------


def _key(**kw):
    base = dict(grid=(2, 4), block_hw=(16, 32), radius=1, fuse=2,
                dtype="float32", boundary="zero", kernel="monolithic",
                col_mode="strided")
    base.update(kw)
    return channels.ChannelKey(**base)


def test_channel_plan_identity_and_reuse():
    channels.reset()
    p1 = channels.plan_for(_key())
    p2 = channels.plan_for(_key())
    assert p1 is p2  # the SAME bound object, not an equal rebuild
    assert channels.stats() == {"builds": 1, "hits": 1}
    channels.plan_for(_key(fuse=4))          # new identity
    channels.plan_for(_key(col_mode="packed"))
    channels.plan_for(_key(kernel="tiled"))
    assert channels.stats()["builds"] == 4
    channels.reset()
    assert channels.stats() == {"builds": 0, "hits": 0}


def test_channel_plan_rejects_unresolved_auto():
    with pytest.raises(ValueError, match="resolved, never 'auto'"):
        _key(col_mode="auto")


def test_monolithic_slab_geometry():
    """The plan's slab table IS halo.halo_exchange's slab math: row
    slabs d-deep at interior columns, column slabs at FULL padded
    height (two-hop corners), SPMD-symmetric src/dst pairing."""
    plan = channels.plan_for(_key())
    h, w, d = 16, 32, 2
    up = plan.slab("up")
    assert (up.src_rows, up.src_cols) == ((d, 2 * d), (d, d + w))
    assert (up.dst_rows, up.dst_cols) == ((h + d, h + 2 * d), (d, d + w))
    assert up.nbr == (-1, 0) and up.sem == channels.SEM_UP
    left = plan.slab("left")
    assert left.src_rows == (0, h + 2 * d)
    assert left.src_cols == (d, 2 * d)
    assert left.dst_cols == (w + d, w + 2 * d)
    assert left.nbr == (0, -1)
    # Strided plans never stage; packed plans stage only with a remote
    # column partner.
    assert not plan.packed_cols
    assert channels.plan_for(_key(col_mode="packed")).packed_cols


def test_degenerate_plan_has_no_channels():
    """1x1 grid: NO slabs, NO staging — the machinery statically elides
    (the ISSUE's degenerate-1x1 satellite)."""
    for cm in ("packed", "strided"):
        plan = channels.plan_for(_key(grid=(1, 1), col_mode=cm))
        assert plan.slabs() == ()
        assert not plan.packed_cols
        assert not plan.row_wrap and not plan.col_wrap
    # Periodic self-wrap axes are wraps, not channels.
    plan = channels.plan_for(_key(grid=(1, 1), boundary="periodic",
                                  block_hw=(16, 32)))
    assert plan.slabs() == () and plan.row_wrap and plan.col_wrap


def test_registry_persistent_bit_and_costmodel_mirror():
    from parallel_convolution_tpu.tuning import costmodel
    from parallel_convolution_tpu.utils.config import BACKENDS

    for b in BACKENDS:
        assert kernel_forms.persistent_capable(b) == (
            b in costmodel.PERSISTENT_BACKENDS)
    assert kernel_forms.persistent_capable("pallas_rdma")
    assert not kernel_forms.persistent_capable("no_such_form")


def test_degenerate_static_elision_lowered_identical():
    """On a 1x1 grid both column transports (and both completion
    ledgers) must compile the IDENTICAL program — pinned at the lowered
    text level, the 'verbatim serialized program' claim."""
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((1, 1))
    x = np.zeros((1, 24, 40), np.float32)

    def lowered(col_mode, partitioned, overlap=False):
        def body(v):
            return pallas_rdma.fused_rdma_step(
                v, filt, (1, 1), "zero", quantize=True, fuse=2,
                valid_hw=(24, 40), overlap=overlap, col_mode=col_mode,
                partitioned=partitioned)
        return jax.jit(jax_compat.shard_map(
            body, mesh=mesh, in_specs=P(None, *AXES),
            out_specs=P(None, *AXES), check_vma=False)).lower(x).as_text()

    base = lowered("strided", True)
    assert lowered("packed", True) == base
    assert lowered("strided", False) == base
    # Under overlap the region-split program differs from serialized (as
    # before r16), but the column transport still elides completely:
    # packed and strided lower to the identical overlapped program.
    ov = lowered("strided", True, overlap=True)
    assert lowered("packed", True, overlap=True) == ov


# ---------------------------------------------------------------------------
# Partitioned completion: wait-set soundness (the property tests).
# ---------------------------------------------------------------------------


def _rects_overlap(a, b):
    (ar0, ar1, ac0, ac1), (br0, br1, bc0, bc1) = a, b
    return ar0 < br1 and br0 < ar1 and ac0 < bc1 and bc0 < ac1


@pytest.mark.parametrize("h,w,d", [(32, 48, 2), (8, 8, 4), (5, 40, 2),
                                   (3, 3, 2), (16, 4, 1), (64, 64, 8),
                                   (7, 64, 3)])
def test_monolithic_region_wait_sets_exact(h, w, d):
    """Every region's wait set == exactly the slab channels whose
    inbound write rect its pad-coordinate read window overlaps — no
    missed wait (a race with an in-flight DMA), no extra wait (lost
    overlap).  Independent brute-force interval check, including the
    degenerate all-rim geometries."""
    from parallel_convolution_tpu.ops.pallas_rdma import (
        overlap_region_slabs, overlap_regions,
    )

    writes = {
        "up": (0, d, d, d + w),
        "down": (h + d, h + 2 * d, d, d + w),
        "left": (0, h + 2 * d, 0, d),
        "right": (0, h + 2 * d, w + d, w + 2 * d),
    }
    regions = overlap_region_slabs(h, w, d)
    # Same partition as overlap_regions — every output pixel once.
    cover = np.zeros((h, w), np.int32)
    for _label, (r0, r1, c0, c1), _waits in regions:
        cover[r0:r1, c0:c1] += 1
    np.testing.assert_array_equal(cover, np.ones((h, w), np.int32))
    interior, _rb, _cb = overlap_regions(h, w, d)
    for label, rect, waits in regions:
        read = (rect[0], rect[1] + 2 * d, rect[2], rect[3] + 2 * d)
        want = frozenset(name for name, wr in writes.items()
                         if _rects_overlap(read, wr))
        assert waits == want, (label, rect, waits, want)
        if label == "interior":
            assert waits == frozenset()
    # Schedule order: interior first, then bands (the compute order the
    # kernel walks).
    assert [lb for lb, _, _ in regions][:len(interior)] == (
        ["interior"] * len(interior))


@pytest.mark.parametrize("grid", [(2, 4), (2, 2), (1, 8), (4, 1)])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
@pytest.mark.parametrize("fuse", [1, 2, 4])
def test_tiled_window_wait_sets_exact(grid, boundary, fuse):
    """The ISSUE's property matrix: for every window of a multi-window
    tiled launch, ``tiled_window_hazards`` == brute-force intersection
    of the window's (ext_h, ext_w) read region with each direction's
    transferred band — and every live band is retired by SOME window
    (the semaphore-hygiene half of soundness)."""
    from parallel_convolution_tpu.ops.pallas_rdma import (
        tiled_window_hazards,
    )

    sub_v, lane = 8, 128
    h, w = 32, 256            # per-device block: multi-window grid
    th, tw = 8, 128
    d = 1 * fuse
    assert d <= min(sub_v, lane)
    gh, gw = -(-h // th), -(-w // tw)
    ext_h, ext_w = th + 2 * sub_v, tw + 2 * lane
    bands = {
        "up": (0, sub_v, lane, lane + w),
        "down": (h + sub_v, h + 2 * sub_v, lane, lane + w),
        "left": (0, h + 2 * sub_v, lane, 2 * lane),
        "right": (0, h + 2 * sub_v, w, w + lane),
    }
    # Band WRITE rects (dst side): left ghost lands at cols [0, lane),
    # right ghost at [w+lane, w+2lane) — the read-hazard rects.
    dst = {
        "up": (0, sub_v, lane, lane + w),
        "down": (h + sub_v, h + 2 * sub_v, lane, lane + w),
        "left": (0, h + 2 * sub_v, 0, lane),
        "right": (0, h + 2 * sub_v, w + lane, w + 2 * lane),
    }
    covered = {k: False for k in dst}
    for wi in range(gh):
        for wj in range(gw):
            hz = tiled_window_hazards(wi, wj, th=th, tw=tw, h=h, w=w,
                                      sub_v=sub_v, lane=lane)
            read = (wi * th, wi * th + ext_h, wj * tw, wj * tw + ext_w)
            for name, rect in dst.items():
                want = _rects_overlap(read, rect)
                assert bool(hz[name]) == want, (wi, wj, name)
                covered[name] = covered[name] or want
    # Every direction's inbound band is touched by at least one window:
    # its semaphores provably retire inside the grid (no hang, no leak)
    # — for ANY of the matrix's grids/boundaries, since existence only
    # prunes waits at runtime, never adds them.
    assert all(covered.values()), covered
    assert grid and boundary  # matrix parameters exercise the claim set


# ---------------------------------------------------------------------------
# Byte proofs: degenerate grids on any jax; full protocol under the
# faithful interpreter (skip-with-cause on stock jax).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("boundary", ["zero", "periodic"])
@pytest.mark.parametrize("col_mode", ["packed", "strided"])
def test_degenerate_monolithic_tiers(boundary, col_mode):
    """1x1 grid: serialized == r12 phase == per-slab partitioned ==
    oracle for both column transports (the channel machinery statically
    elided; the region-split compute is the only live difference)."""
    filt = filters.get_filter("blur3")
    dims = (24, 36) if boundary == "periodic" else (37, 53)
    img = imageio.generate_test_image(*dims, "grey", seed=61)
    want = oracle.run_serial_u8(img, filt, 4, boundary=boundary)
    outs = {}
    for tier, (ov, part) in (("ser", (False, True)),
                             ("phase", (True, False)),
                             ("slab", (True, True))):
        outs[tier] = _run(img, filt, 4, (1, 1), boundary=boundary,
                          fuse=2, overlap=ov, col_mode=col_mode,
                          partitioned=part)
    np.testing.assert_array_equal(outs["slab"], want)
    np.testing.assert_array_equal(outs["slab"], outs["ser"])
    np.testing.assert_array_equal(outs["phase"], outs["ser"])


@pytest.mark.parametrize("col_mode", ["packed", "strided"])
def test_degenerate_tiled_tiers(col_mode):
    """Forced tiled kernel on 1x1 (multi-window grid): all three
    channel tiers byte-identical, both transports."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(96, 384, "grey", seed=62)
    want = oracle.run_serial_u8(img, filt, 4)
    outs = {}
    for tier, (ov, part) in (("ser", (False, True)),
                             ("slab", (True, True))):
        outs[tier] = _run(img, filt, 4, (1, 1), fuse=2, overlap=ov,
                          col_mode=col_mode, partitioned=part,
                          tiled=True, tile=(32, 128))
    np.testing.assert_array_equal(outs["slab"], want)
    np.testing.assert_array_equal(outs["slab"], outs["ser"])


@pytest.mark.parametrize("mesh_shape", [(1, 8), (4, 1), (2, 2)])
@pytest.mark.parametrize("partitioned", [True, False])
def test_tiled_one_long_axis_traces_every_ledger(mesh_shape, partitioned):
    """TRACE-level regression pin (no faithful interpreter needed —
    jax.eval_shape runs the kernel's python body): the tiled kernel's
    retirement helpers must be constructible on grids with a MISSING
    axis, because the legacy phase ledger traces them under dynamic
    predicates.  First cut crashed with AttributeError on (1, N) grids
    (plan.slab('up') is None when R == 1)."""
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh(mesh_shape)
    R, C = mesh_shape
    x = np.zeros((1, R * 32, C * 256), np.float32)

    def body(v):
        return pallas_rdma.fused_rdma_step(
            v, filt, mesh_shape, "zero", quantize=True, tiled=True,
            tile=(8, 128), fuse=1, valid_hw=(R * 32, C * 256),
            overlap=True, partitioned=partitioned)

    jax.eval_shape(jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES),
        out_specs=P(None, *AXES), check_vma=False)), x)


@needs_faithful_interpret
@pytest.mark.parametrize("mesh_shape", [(2, 4), (2, 2), (1, 8), (4, 1)])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
def test_partitioned_monolithic_protocol(mesh_shape, boundary):
    """Full protocol under REAL (simulated) in-flight DMAs: per-slab
    partitioned == r12 phase == serialized == oracle on the ISSUE's
    grid matrix, both boundaries, fuse 1/2/4, both column transports."""
    filt = filters.get_filter("blur3")
    if boundary == "periodic":
        dims = (mesh_shape[0] * 16, mesh_shape[1] * 16)
    else:
        dims = (mesh_shape[0] * 16 + 5, mesh_shape[1] * 16 + 3)
    img = imageio.generate_test_image(*dims, "grey", seed=63)
    for fuse in (1, 2, 4):
        iters = 2 * fuse
        want = oracle.run_serial_u8(img, filt, iters, boundary=boundary)
        for cm in ("packed", "strided"):
            slab = _run(img, filt, iters, mesh_shape, boundary=boundary,
                        fuse=fuse, overlap=True, col_mode=cm,
                        partitioned=True)
            phase = _run(img, filt, iters, mesh_shape, boundary=boundary,
                         fuse=fuse, overlap=True, col_mode=cm,
                         partitioned=False)
            ser = _run(img, filt, iters, mesh_shape, boundary=boundary,
                       fuse=fuse, overlap=False, col_mode=cm)
            np.testing.assert_array_equal(slab, want)
            np.testing.assert_array_equal(slab, phase)
            np.testing.assert_array_equal(slab, ser)


@needs_faithful_interpret
@pytest.mark.parametrize("col_mode", ["packed", "strided"])
def test_partitioned_tiled_protocol(col_mode):
    """Tiled kernel on 2x2: per-slab ledger + rotated rim-last
    traversal + packed/strided transport reproduce serialized bytes."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(64, 256, "grey", seed=64)
    for fuse in (1, 2):
        slab = _run(img, filt, 2 * fuse, (2, 2), fuse=fuse, overlap=True,
                    col_mode=col_mode, partitioned=True, tiled=True,
                    tile=(16, 128))
        ser = _run(img, filt, 2 * fuse, (2, 2), fuse=fuse, overlap=False,
                   col_mode=col_mode, tiled=True, tile=(16, 128))
        want = oracle.run_serial_u8(img, filt, 2 * fuse)
        np.testing.assert_array_equal(slab, ser)
        np.testing.assert_array_equal(slab, want)


@needs_faithful_interpret
def test_partitioned_race_detector():
    """The interpreter's vector-clock race detector over the per-slab
    protocol with the packed transport: every region read must be
    provably ordered against the in-flight slab/stage writes."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.pallas import tpu as pltpu

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    img = imageio.generate_test_image(24, 36, "grey", seed=65)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        import jax.lax as lax

        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, (2, 2), "zero", quantize=True, interpret=params,
                fuse=2, valid_hw=(24, 36), overlap=True, col_mode="packed",
                partitioned=True)
        return lax.fori_loop(0, 2, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(img, filt, 4)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


# ---------------------------------------------------------------------------
# Cost model: the split setup/transfer exchange term, pinned constants.
# ---------------------------------------------------------------------------


def test_exchange_setup_transfer_split_pinned():
    """Drift guard for the round-16 constants and the split's algebra:
    persistent zeroes EXACTLY the setup term; the column-transport
    terms recompute from their documented formulas."""
    from parallel_convolution_tpu.tuning import costmodel as cm

    assert cm.EXCHANGE_SETUP_S == 1.5e-6
    assert cm.STRIDED_ROW_DESC_S == 15e-9
    assert cm.PERSISTENT_BACKENDS == ("pallas_rdma",)
    hw = cm.TPU_V5E
    grid, block, radius, fuse, storage = (2, 4), (256, 128), 1, 4, "f32"
    T, (bh, bw) = fuse, block
    non = cm.exchange_seconds_per_px_iter(grid, block, radius, fuse,
                                          storage, hw, persistent=False,
                                          col_mode="packed")
    per = cm.exchange_seconds_per_px_iter(grid, block, radius, fuse,
                                          storage, hw, persistent=True,
                                          col_mode="packed")
    assert non - per == pytest.approx(
        2.0 * cm.EXCHANGE_SETUP_S / (T * bh * bw), rel=1e-12)
    # Column-transport terms from the documented formulas.
    d = radius * T
    rows = bh + 2 * d
    assert cm.col_transport_seconds_per_round(
        block, radius, fuse, storage, hw, "strided") == pytest.approx(
        2.0 * rows * cm.STRIDED_ROW_DESC_S, rel=1e-12)
    assert cm.col_transport_seconds_per_round(
        block, radius, fuse, storage, hw, "packed") == pytest.approx(
        2.0 * 4.0 * rows * d * 4 / (hw.hbm_gbps * 1e9), rel=1e-12)
    # A 1-extent column axis has no transport at all (and zero total on
    # a 1x1 grid — both terms statically elided, like the kernels).
    row_only = cm.exchange_seconds_per_px_iter(
        (4, 1), block, radius, fuse, storage, hw, persistent=True,
        col_mode="strided")
    assert row_only == cm.exchange_seconds_per_px_iter(
        (4, 1), block, radius, fuse, storage, hw, persistent=True,
        col_mode="packed")
    assert cm.exchange_seconds_per_px_iter(
        (1, 1), block, radius, fuse, storage, hw) == 0.0
    with pytest.raises(ValueError, match="col_mode"):
        cm.col_transport_seconds_per_round(block, radius, fuse, storage,
                                           hw, "auto")


def test_pick_col_mode_crossover_and_determinism():
    """The derived-datatypes decision: thin slabs stage (packed), deep
    slabs go direct strided — and the verdict is the argmin of the two
    transport terms by construction, deterministic per identity."""
    from parallel_convolution_tpu.tuning import costmodel as cm

    hw = cm.TPU_V5E
    for block, radius, fuse, storage in (
            ((256, 128), 1, 1, "f32"), ((256, 128), 1, 8, "f32"),
            ((2048, 1024), 2, 4, "bf16"), ((64, 128), 1, 2, "u8")):
        pick = cm.pick_col_mode((2, 4), block, radius, fuse, storage, hw)
        p = cm.col_transport_seconds_per_round(block, radius, fuse,
                                               storage, hw, "packed")
        s = cm.col_transport_seconds_per_round(block, radius, fuse,
                                               storage, hw, "strided")
        assert pick == ("packed" if p <= s else "strided")
        assert pick == cm.pick_col_mode((2, 4), block, radius, fuse,
                                        storage, hw)
    assert cm.pick_col_mode((4, 1), (256, 128), 1, 1, "f32", hw) == "packed"


def test_predict_prices_col_mode_only_on_persistent_tiers():
    from parallel_convolution_tpu.tuning import costmodel as cm

    hw = cm.TPU_V5E
    args = ("f32", 4, None, (1, 4096, 4096), (2048, 1024), (2, 4), 3,
            False, True, hw)
    assert cm.predict_seconds_per_px_iter(
        "pallas", *args, col_mode="packed") == cm.predict_seconds_per_px_iter(
        "pallas", *args, col_mode="strided")
    assert cm.predict_seconds_per_px_iter(
        "pallas_rdma", *args, col_mode="packed") != (
        cm.predict_seconds_per_px_iter(
            "pallas_rdma", *args, col_mode="strided"))


# ---------------------------------------------------------------------------
# Resolution + threading: dispatch, tuner, plans, bench, serving.
# ---------------------------------------------------------------------------


def test_resolve_col_mode_clamps():
    mesh = _mesh((2, 4))
    # Non-persistent forms: the knob is inert, normalized to 'packed'.
    assert step.resolve_col_mode("strided", "shifted", mesh, (8, 8), 1, 1,
                                 "f32") == "packed"
    assert step.resolve_col_mode(None, "pallas", mesh, (8, 8), 1, 1,
                                 "f32") == "packed"
    # Persistent form with a remote column axis: explicit honored, auto
    # goes to the model.
    assert step.resolve_col_mode("strided", "pallas_rdma", mesh, (8, 8),
                                 1, 1, "f32") == "strided"
    auto = step.resolve_col_mode("auto", "pallas_rdma", mesh, (8, 8), 1,
                                 1, "f32")
    assert auto in ("packed", "strided")
    assert step.resolve_col_mode(None, "pallas_rdma", mesh, (8, 8), 1, 1,
                                 "f32") == auto
    # No remote column axis: even an explicit 'strided' normalizes —
    # both transports compile the identical statically-elided program,
    # so one program gets ONE resolved identity (keys never split).
    for shape in ((1, 1), (4, 1)):
        assert step.resolve_col_mode("strided", "pallas_rdma",
                                     _mesh(shape), (8, 8), 1, 1,
                                     "f32") == "packed"
    with pytest.raises(ValueError, match="col_mode"):
        step.resolve_col_mode("dense", "pallas_rdma", mesh, (8, 8), 1, 1,
                              "f32")
    assert step.clamp_col_mode("strided", "pallas") == "packed"
    assert step.clamp_col_mode("strided", "pallas_rdma") == "strided"


def test_candidate_space_col_modes():
    from parallel_convolution_tpu.tuning import search
    from parallel_convolution_tpu.tuning.plans import Workload

    filt = filters.get_filter("blur3")
    w = Workload.from_mesh(_mesh((2, 4)), filt, (1, 512, 512))
    cands = search.enumerate_candidates(w)
    rdma = {c.col_mode for c in cands if c.backend == "pallas_rdma"}
    assert rdma == {"packed", "strided"}
    assert {c.col_mode for c in cands
            if c.backend != "pallas_rdma"} == {"packed"}
    # A pinned mode prunes the persistent tier's pair to one.
    pinned = search.enumerate_candidates(w, col_mode="strided")
    assert {c.col_mode for c in pinned
            if c.backend == "pallas_rdma"} == {"strided"}
    # No remote column axis: both modes compile the identical program —
    # only the canonical twin is enumerated (no wasted measurements).
    w41 = Workload.from_mesh(_mesh((4, 1)), filt, (1, 512, 512))
    assert {c.col_mode for c in search.enumerate_candidates(w41)} == {
        "packed"}


def test_plan_record_col_mode_roundtrip(tmp_path):
    """Plans persist col_mode; legacy records (no key) load as 'packed'
    — byte-identical to every mode, so no schema bump."""
    from parallel_convolution_tpu.tuning.plans import (
        PLAN_SCHEMA, Plan, PlanCache, Workload,
    )

    filt = filters.get_filter("blur3")
    w = Workload.from_mesh(_mesh((2, 4)), filt, (1, 512, 512))
    cache = PlanCache()
    cache.put(w, Plan("pallas_rdma", fuse=4, col_mode="strided",
                      source="measured"))
    p = str(tmp_path / "plans.json")
    cache.save(p)
    loaded = PlanCache.load(p)
    plan = loaded.exact(w)
    assert plan is not None and plan.col_mode == "strided"
    rec = loaded.records[w.key()]
    rec.pop("col_mode")   # a pre-r16 tuner's record
    assert Plan.from_record(rec).col_mode == "packed"
    assert PLAN_SCHEMA == 1  # explicitly NO schema bump


def test_resolve_from_plan_col_mode():
    from parallel_convolution_tpu import tuning
    from parallel_convolution_tpu.tuning.plans import Plan, PlanCache, Workload

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 4))
    w = Workload.from_mesh(mesh, filt, (1, 512, 512))
    cache = PlanCache()
    cache.put(w, Plan("pallas_rdma", fuse=4, col_mode="strided",
                      source="measured"))
    res = tuning.resolve(mesh, filt, (1, 512, 512), plans=cache)
    assert (res.backend, res.col_mode) == ("pallas_rdma", "strided")
    # Explicit request overrides the stored verdict.
    res = tuning.resolve(mesh, filt, (1, 512, 512), plans=cache,
                         col_mode="packed")
    assert res.col_mode == "packed"
    # A stored strided verdict on a NON-persistent plan normalizes.
    cache2 = PlanCache()
    cache2.put(w, Plan("shifted", col_mode="strided", source="measured"))
    res = tuning.resolve(mesh, filt, (1, 512, 512), plans=cache2)
    assert res.col_mode == "packed"


def test_bench_row_stamps_col_mode():
    from parallel_convolution_tpu.utils import bench

    filt = filters.get_filter("blur3")
    # 1x1 grid: no column transport exists, so even an explicit
    # 'strided' request stamps the canonical normalized label — the row
    # states the PROGRAM, and there is only one program here.
    row = bench.bench_iterate((16, 128), filt, 2, mesh=_mesh((1, 1)),
                              backend="pallas_rdma", reps=1,
                              col_mode="strided")
    assert row["col_mode"] == "packed"
    assert row["effective_backend"] == "pallas_rdma"
    row = bench.bench_iterate((16, 64), filt, 2, mesh=_mesh((1, 1)),
                              backend="shifted", reps=1,
                              col_mode="strided")
    assert row["col_mode"] == "packed"  # inert off the persistent tier


def test_probe_key_distinguishes_col_mode():
    from parallel_convolution_tpu.resilience import degrade

    filt = filters.get_filter("blur3")
    mesh = _mesh((1, 1))
    k1 = degrade._probe_key(mesh, filt, "pallas_rdma", True, 1, "zero",
                            None, False, "f32", (8, 8), overlap=False,
                            col_mode="packed")
    k2 = degrade._probe_key(mesh, filt, "pallas_rdma", True, 1, "zero",
                            None, False, "f32", (8, 8), overlap=False,
                            col_mode="strided")
    assert k1 != k2


def test_engine_key_carries_resolved_col_mode():
    from parallel_convolution_tpu.serving.engine import WarmEngine

    # A grid WITH a remote column axis: the two transports are distinct
    # compiled programs, so they key separately (resolve_key never
    # compiles — safe on stock jax).
    eng24 = WarmEngine(mesh=_mesh((2, 4)))
    k_p, _ = eng24.resolve_key((1, 64, 512), backend="pallas_rdma",
                               iters=2, col_mode="packed")
    k_s, _ = eng24.resolve_key((1, 64, 512), backend="pallas_rdma",
                               iters=2, col_mode="strided")
    assert k_p.col_mode == "packed" and k_s.col_mode == "strided"
    assert k_p != k_s
    eng = WarmEngine(mesh=_mesh((1, 1)))
    # None (absent) and 'auto' resolve to the SAME concrete key — one
    # warm executable for auto + explicit requests, the backend/overlap
    # rule applied to the column transport.
    k_none, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma",
                                iters=2)
    k_auto, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma",
                                iters=2, col_mode="auto")
    assert k_none == k_auto
    assert k_none.col_mode in ("packed", "strided")
    # No remote column axis: an explicit 'strided' request compiles the
    # IDENTICAL statically-elided program, so it shares the key too —
    # never two warm entries for one executable.
    k_str1, _ = eng.resolve_key((1, 16, 128), backend="pallas_rdma",
                                iters=2, col_mode="strided")
    assert k_str1 == k_none
    # Non-persistent backends key the canonical inert label.
    k_sh, _ = eng.resolve_key((1, 16, 128), backend="shifted", iters=2,
                              col_mode="strided")
    assert k_sh.col_mode == "packed"
    with pytest.raises(ValueError, match="col_mode"):
        from parallel_convolution_tpu.serving.engine import EngineKey

        EngineKey(shape=(1, 16, 128), col_mode="auto").validate()


def test_service_response_stamps_col_mode():
    from parallel_convolution_tpu.serving.service import (
        ConvolutionService, Request,
    )

    img = imageio.generate_test_image(16, 128, "grey", seed=66)
    svc = ConvolutionService(mesh=_mesh((1, 1)), max_delay_s=0.001)
    try:
        # 1x1 grid: the strided request normalizes (no column transport
        # exists) and the response stamps the RESOLVED value.
        res = svc.submit(Request(image=img, iters=2,
                                 backend="pallas_rdma",
                                 col_mode="strided"))
        assert res.ok and res.col_mode == "packed"
        want = oracle.run_serial_u8(img, filters.get_filter("blur3"), 2)
        np.testing.assert_array_equal(res.image, want)
        res2 = svc.submit(Request(image=img, iters=2, backend="shifted"))
        assert res2.ok and res2.col_mode == "packed"
    finally:
        svc.close()


def test_wire_codec_roundtrips_col_mode():
    from parallel_convolution_tpu.serving import frontend

    req = frontend.decode_request({
        "rows": 4, "cols": 4, "mode": "grey",
        "image_b64": __import__("base64").b64encode(
            bytes(16)).decode("ascii"),
        "col_mode": "strided"})
    assert req.col_mode == "strided"
    req = frontend.decode_request({
        "rows": 4, "cols": 4, "mode": "grey",
        "image_b64": __import__("base64").b64encode(
            bytes(16)).decode("ascii")})
    assert req.col_mode is None


# ---------------------------------------------------------------------------
# Channel reuse through real runs + the slab-wait attribution series.
# ---------------------------------------------------------------------------


def test_channel_reuse_flat_across_converge_chunks():
    """A fused multi-chunk converge run builds exactly one plan per
    distinct exchange identity (the fused chunk + the pair step) and
    every later chunk reuses them — the acceptance criterion's
    'descriptor-plan builds == distinct identities, flat'."""
    filt = filters.get_filter("blur3")
    mesh = _mesh((1, 1))
    img = imageio.generate_test_image(24, 32, "grey", seed=67)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    channels.reset()
    out, iters = step.sharded_converge(
        x, filt, tol=0.0, max_iters=6, check_every=3, mesh=mesh,
        quantize=True, backend="pallas_rdma", fuse=2)
    first = channels.stats()
    assert first["builds"] == 2  # fused chunk + single-step identities
    assert iters == 6
    out2, _ = step.sharded_converge(
        x, filt, tol=0.0, max_iters=12, check_every=3, mesh=mesh,
        quantize=True, backend="pallas_rdma", fuse=2)
    assert channels.stats()["builds"] == first["builds"]
    want = oracle.run_serial_u8(img, filt, 6)
    got = np.clip(np.rint(np.asarray(out)), 0, 255).astype(np.uint8)[0]
    np.testing.assert_array_equal(got, want)


def test_mg_level_schedule_caches_channel_identities():
    from parallel_convolution_tpu.solvers import multigrid as mg

    filt = filters.get_filter("blur3")
    levels = mg.plan_levels(_mesh((1, 1)), (96, 64), filt.radius, "zero")
    assert len(levels) > 1
    channels.reset()
    keys = mg.warm_level_channels(levels, filt.radius, "zero", "packed")
    assert len(keys) == len(levels)
    assert channels.stats()["builds"] == len(set(keys))
    mg.warm_level_channels(levels, filt.radius, "zero", "packed")
    s = channels.stats()
    assert s["builds"] == len(set(keys))  # flat: bound once per level
    assert s["hits"] >= len(keys)
    # Each level's identity states ITS OWN geometry.
    assert [k.block_hw for k in keys] == [lv.block_hw for lv in levels]


def test_slab_wait_series_and_event_col_mode():
    """record_step with a wall emits the per-slab wait counter split by
    direction x exposed/hidden, shares summing to the exchange wall."""
    from parallel_convolution_tpu.obs import attribution, metrics

    was = metrics.enabled()
    metrics.reset()
    metrics.set_enabled(True)
    try:
        att = attribution.record_step(
            backend="pallas_rdma", grid=(2, 4), block_hw=(256, 128),
            radius=1, fuse=4, iters=8, channels=1, storage="f32",
            boundary="zero", wall_s=0.5, shape=(1, 512, 512),
            platform="tpu", device_kind="tpu-v5e", overlap=True,
            col_mode="strided")
        assert att is not None
        snap = metrics.snapshot()
        m = next(x for x in snap["metrics"]
                 if x["name"] == "pctpu_halo_slab_wait_seconds")
        got = {(s["labels"]["direction"], s["labels"]["which"]): s["value"]
               for s in m["series"]}
        assert {d for d, _ in got} == {"north", "south", "east", "west"}
        assert {w for _, w in got} == {"exposed", "hidden"}
        exposed = sum(v for (d, w), v in got.items() if w == "exposed")
        assert exposed == pytest.approx(
            0.5 * att["exchange_fraction"], rel=1e-6)
    finally:
        metrics.reset()
        metrics.set_enabled(was)
