"""Fused remote-DMA halo kernel: bit-exactness + race-freedom on CPU mesh.

TPU interpret mode simulates remote DMAs, semaphores and per-device
buffers on the virtual CPU mesh, so the cross-device protocol (two-phase
sends, conditional boundary waits, corner propagation) is executed for
real — this is the reference's Isend/Irecv tier moved inside the kernel.
Perf on real multi-chip hardware is explicitly NOT validated here (no
such hardware in this environment); semantics are.
"""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio
from parallel_convolution_tpu.utils import jax_compat

# The cross-device protocol needs the DMA-faithful TPU interpreter
# (simulated remote copies / semaphores / barrier).  On a jax without it
# (0.4.x: no lowering for those primitives on CPU) the multi-device tests
# skip with cause; the degenerate-grid tests below still run — extent-1
# axes statically elide every RDMA construct (pallas_rdma._when), so the
# full fuse compute path is pinned on any jax.
needs_faithful_interpret = pytest.mark.skipif(
    not jax_compat.HAS_TPU_INTERPRET,
    reason="DMA-faithful TPU interpret mode unavailable in this jax "
           "(needs current jax, or real silicon)")


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (2, 4), (4, 1),
                                        (1, 8)])
@needs_faithful_interpret
def test_rdma_bitexact_vs_oracle(grey_odd, mesh_shape):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 4)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 4, mesh=_mesh(mesh_shape),
                               quantize=True, backend="pallas_rdma")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_rgb_radius2(rgb_odd):
    # radius-2: 2-wide ghost slabs + 2-hop corners through the RDMA path
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 3)
    x = imageio.interleaved_to_planar(rgb_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               quantize=True, backend="pallas_rdma")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_periodic(grey_small):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_small, filt, 4, boundary="periodic")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out = step.sharded_iterate(x, filt, 4, mesh=_mesh((2, 2)), quantize=True,
                               backend="pallas_rdma", boundary="periodic")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_u8_storage(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 5)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 5, mesh=_mesh((2, 2)), quantize=True,
                               backend="pallas_rdma", storage="u8")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_race_detector(grey_small):
    """The interpreter's vector-clock race detector over the full protocol.

    This is the framework's race-detection tier (SURVEY.md §5 sanitizers):
    local ghost zeroing vs inbound remote writes are disjoint by design,
    and detect_races=True proves it on every (device, phase) pair.
    """
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)[
        :, :24, :36]
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        return pallas_rdma.fused_rdma_step(
            v, filt, (2, 2), "zero", quantize=True, interpret=params)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(x[0].astype(np.uint8), filt, 1)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


@needs_faithful_interpret
def test_rdma_back_to_back_race(grey_small):
    """≥2 chained invocations under the race detector (cross-invocation fix).

    The iteration driver runs the kernel back-to-back inside a fori_loop;
    the start-of-kernel neighbor barrier must keep a fast device's
    iteration-N+1 remote copies out of a slow neighbor's still-live
    iteration-N scratch.  detect_races=True checks every (device, phase)
    pair across all three chained invocations, and the result must stay
    bit-exact vs three serial oracle steps.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)[
        :, :24, :36]
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, (2, 2), "zero", quantize=True, interpret=params)
        return lax.fori_loop(0, 3, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(x[0].astype(np.uint8), filt, 3)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)
    assert jnp.issubdtype(out.dtype, jnp.floating)


def test_collective_id_registry():
    from parallel_convolution_tpu.ops import collective_ids

    assert collective_ids.collective_id("rdma_halo_stencil") == 1
    with pytest.raises(KeyError, match="no collective_id"):
        collective_ids.collective_id("nope")
    # ids must be unique — a collision in the static table is a code bug
    ids = list(collective_ids._COLLECTIVE_IDS.values())
    assert len(ids) == len(set(ids))


def _run_rdma_tiled(img, filt, iters, mesh_shape, tile=None, tiled=True,
                    boundary="zero", pad_operand=None, fuse=1,
                    storage=np.float32):
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    mesh = _mesh(mesh_shape)
    x = imageio.interleaved_to_planar(img).astype(storage)
    valid_hw = None if boundary == "periodic" else img.shape[:2]

    def body(v):
        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, mesh_shape, boundary, quantize=True,
                tiled=tiled, tile=tile, pad_operand=pad_operand,
                fuse=fuse, valid_hw=valid_hw)
        import jax.lax as lax

        return lax.fori_loop(0, iters, one, v)

    out = jax.jit(jax_compat.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    return np.asarray(out)[0].astype(np.uint8)


@needs_faithful_interpret
def test_rdma_tiled_bitexact_corners():
    """Forced-tiled variant: multi-window grid, 2 chained iterations, 2×2
    mesh — corners must propagate through the aligned-band two-phase
    exchange and match the oracle bit-for-bit."""
    filt = filters.get_filter("blur3")
    # per-device block 32x128 with tile (16, 128): 2x1 window grid per
    # block, plus chained invocations through the neighbor barrier
    img = imageio.generate_test_image(64, 256, "grey", seed=21)
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128))
    want = oracle.run_serial_u8(img, filt, 2)
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_pad_operand_bitexact():
    """Operand-backed HBM pad (discarded-second-output workaround for
    the chipless compile helper's HBM-scratch rejection, round-5 probe
    ladder): same bytes as the scratch form and as the oracle, through
    chained iterations with corner propagation."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(64, 256, "grey", seed=23)
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128),
                          pad_operand=True)
    want = oracle.run_serial_u8(img, filt, 2)
    np.testing.assert_array_equal(got, want)
    scratch_form = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128),
                                   pad_operand=False)
    np.testing.assert_array_equal(got, scratch_form)


@needs_faithful_interpret
def test_rdma_tiled_pad_operand_periodic():
    """Operand mode under the torus: self-wrap axes fill ghosts by local
    aligned copies; the zero-filled operand must not leak through."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(32, 256, "grey", seed=24)
    got = _run_rdma_tiled(img, filt, 2, (1, 2), tile=(16, 128),
                          boundary="periodic", pad_operand=True)
    want = oracle.run_serial_u8(img, filt, 2, boundary="periodic")
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_gaussian5_radius2():
    """radius-2 ghost bands through the tiled exchange (2-hop corners)."""
    filt = filters.get_filter("gaussian5")
    img = imageio.generate_test_image(64, 256, "grey", seed=22)
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128))
    want = oracle.run_serial_u8(img, filt, 2)
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_periodic_wrap():
    """Periodic torus incl. a self-wrap axis (1×2 grid: R==1 wraps to
    itself via local band copies, Cc==2 via remote bands)."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(32, 256, "grey", seed=23)
    got = _run_rdma_tiled(img, filt, 2, (1, 2), tile=(16, 128),
                          boundary="periodic")
    want = oracle.run_serial_u8(img, filt, 2, boundary="periodic")
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_non_dividing_tile():
    """Tile that does not divide the block: the last window row/col of
    the grid covers pad-rim garbage, which the valid-box mask must zero
    — bit-exactness across 2 chained iterations proves it."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(96, 384, "grey", seed=25)
    # blocks 48x192 per device; tile (32, 128) -> 2x2 windows with a
    # 16-row / 64-col rim beyond the block in the last row/col windows
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(32, 128))
    want = oracle.run_serial_u8(img, filt, 2)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", range(4))
@needs_faithful_interpret
def test_rdma_tiled_geometry_fuzz(seed):
    """Seeded random geometries through the tiled kernel: block shapes
    (aligned and ragged), tile sizes, mesh aspects, radii — every combo
    must stay bit-exact vs the oracle.  Catches mask/band geometry bugs
    the hand-picked cases might miss."""
    rng = np.random.default_rng(100 + seed)
    mesh_shape = [(2, 2), (1, 2), (2, 1)][int(rng.integers(3))]
    R, Cc = mesh_shape
    # blocks must satisfy the tiled guard: h >= sublane(8 f32), w >= 128
    bh = int(rng.integers(8, 40))
    bw = 128 + int(rng.integers(0, 130))
    rows, cols = bh * R, bw * Cc
    filt = filters.get_filter(["blur3", "gaussian5"][int(rng.integers(2))])
    tile = (int(rng.integers(1, 5)) * 8, 128)
    iters = int(rng.integers(1, 3))
    img = imageio.generate_test_image(rows, cols, "grey",
                                      seed=int(rng.integers(1000)))
    got = _run_rdma_tiled(img, filt, iters, mesh_shape, tile=tile)
    want = oracle.run_serial_u8(img, filt, iters)
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_auto_tiles_beyond_vmem_bound():
    """Blocks beyond the monolithic kernel's VMEM budget auto-select the
    tiled variant (VERDICT item: 'a block larger than today's VMEM
    bound').  1664×1792 f32 block → 12.3 MB padded f32 + 11.9 MB out >
    the 10 MB budget; the whole-block-in-VMEM kernel could not hold it.
    One step on a 2×1 mesh, bit-exact vs the oracle."""
    from parallel_convolution_tpu.ops import pallas_rdma

    C, h, w = 1, 1664, 1792
    mono = C * (h + 2) * (w + 2) * 4 + C * h * w * 4
    assert mono > pallas_rdma._TILED_VMEM_BYTES

    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(2 * h, w, "grey", seed=24)
    got = _run_rdma_tiled(img, filt, 1, (2, 1), tiled=None)  # auto
    want = oracle.run_serial_u8(img, filt, 1)
    np.testing.assert_array_equal(got, want)


def test_rdma_tiled_rejects_sub_band_blocks():
    """Blocks narrower than one transfer band would self-overlap the
    band copies (undefined on real DMA engines) — must be rejected."""
    import jax.numpy as jnp

    from parallel_convolution_tpu.ops import pallas_rdma

    small = jnp.zeros((1, 8, 64), jnp.float32)
    with pytest.raises(ValueError, match="non-overlapping band"):
        pallas_rdma.fused_rdma_step(small, filters.get_filter("blur3"),
                                    (2, 2), tiled=True)


def test_rdma_auto_untileable_raises():
    """Over-VMEM-budget block + radius too big for aligned bands must be
    a clear error, not a silent fall-through to a Mosaic VMEM failure."""
    import jax.numpy as jnp

    from parallel_convolution_tpu.ops import pallas_rdma

    big = jnp.zeros((1, 2048, 2048), jnp.float32)
    wide = filters.gaussian(19, 3.0)  # r=9 > f32 sublane (8)
    with pytest.raises(ValueError, match="use a finer"):
        pallas_rdma.fused_rdma_step(big, wide, (2, 2))


# ---------------------------------------------------------------------------
# Temporal fusion (fuse=T) inside the RDMA kernels: exchange once, iterate
# T levels in-kernel.  Parity contract: bit-exact vs the serial oracle for
# T single-exchange iterations — both kernels, both boundaries, f32 + u8.
# ---------------------------------------------------------------------------


@needs_faithful_interpret
@pytest.mark.parametrize("fuse", [2, 4])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
def test_rdma_fused_bitexact_monolithic(fuse, boundary):
    """Monolithic kernel, 2x2 CPU mesh: T*r-deep exchange + T in-kernel
    levels must match 8 oracle iterations byte-for-byte.  Zero boundary
    uses awkward odd dims (pad-to-multiple rim -> per-level global-image
    re-masking); periodic uses mesh-divisible dims (required)."""
    filt = filters.get_filter("blur3")
    if boundary == "periodic":
        img = imageio.generate_test_image(32, 48, "grey", seed=31)
    else:
        img = imageio.generate_test_image(37, 53, "grey", seed=31)
    want = oracle.run_serial_u8(img, filt, 8, boundary=boundary)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    out = step.sharded_iterate(x, filt, 8, mesh=_mesh((2, 2)), quantize=True,
                               backend="pallas_rdma", boundary=boundary,
                               fuse=fuse)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_fused_u8_storage(grey_odd):
    """fuse=2 through the driver with the u8 iteration carry."""
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 6)
    out = step.sharded_iterate(
        imageio.interleaved_to_planar(grey_odd).astype(np.float32),
        filt, 6, mesh=_mesh((2, 2)), quantize=True, backend="pallas_rdma",
        storage="u8", fuse=2)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_fused_remainder_path(grey_odd):
    """7 iters at fuse=3 -> two fused chunks + a single-step tail, all
    through the RDMA kernel."""
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 7)
    out = step.sharded_iterate(
        imageio.interleaved_to_planar(grey_odd).astype(np.float32),
        filt, 7, mesh=_mesh((2, 2)), quantize=True, backend="pallas_rdma",
        fuse=3)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
@pytest.mark.parametrize("fuse", [2, 4])
def test_rdma_tiled_fused_bitexact(fuse):
    """Tiled kernel, 2x2 mesh: the sub_v/128-deep aligned bands carry
    r*T live ghost rows/cols; 2 chained fused chunks must equal 2*T
    oracle iterations."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(64, 256, "grey", seed=26)
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128), fuse=fuse)
    want = oracle.run_serial_u8(img, filt, 2 * fuse)
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_fused_periodic():
    """Tiled fuse=2 on the torus incl. a self-wrap axis."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(32, 256, "grey", seed=27)
    got = _run_rdma_tiled(img, filt, 2, (1, 2), tile=(16, 128),
                          boundary="periodic", fuse=2)
    want = oracle.run_serial_u8(img, filt, 4, boundary="periodic")
    np.testing.assert_array_equal(got, want)


@needs_faithful_interpret
def test_rdma_tiled_fused_u8():
    """Tiled fuse through a u8 carry (sublane 32: one band holds 8 live
    ghost rows with room to spare) on a multi-window grid."""
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(64, 256, "grey", seed=28)
    got = _run_rdma_tiled(img, filt, 2, (2, 2), tile=(16, 128), fuse=4,
                          storage=np.uint8)
    want = oracle.run_serial_u8(img, filt, 8)
    np.testing.assert_array_equal(got, want)


# --- Degenerate grids: extent-1 axes statically elide every RDMA
# construct, so these run under ANY jax (no faithful interpreter needed)
# and pin the fused compute path — per-level masking, quantize threading,
# shrink geometry — on both kernels.


@pytest.mark.parametrize("fuse", [2, 4])
@pytest.mark.parametrize("boundary", ["zero", "periodic"])
def test_rdma_fused_degenerate_monolithic(fuse, boundary):
    filt = filters.get_filter("blur3")
    dims = (24, 36) if boundary == "periodic" else (37, 53)
    img = imageio.generate_test_image(*dims, "grey", seed=33)
    want = oracle.run_serial_u8(img, filt, 8, boundary=boundary)
    x = imageio.interleaved_to_planar(img).astype(np.float32)
    out = step.sharded_iterate(x, filt, 8, mesh=_mesh((1, 1)), quantize=True,
                               backend="pallas_rdma", boundary=boundary,
                               fuse=fuse)
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("fuse", [2, 4])
def test_rdma_fused_degenerate_tiled(fuse):
    filt = filters.get_filter("blur3")
    img = imageio.generate_test_image(96, 384, "grey", seed=34)
    # tile (32, 128) does not divide the 96x384 block: the window-rim
    # garbage must die in the tier-1 select before the level loop
    got = _run_rdma_tiled(img, filt, 2, (1, 1), tile=(32, 128), fuse=fuse)
    want = oracle.run_serial_u8(img, filt, 2 * fuse)
    np.testing.assert_array_equal(got, want)


def test_rdma_fused_degenerate_tiled_u8_radius2():
    filt = filters.get_filter("gaussian5")
    img = imageio.generate_test_image(64, 256, "grey", seed=35)
    # r=2, fuse=4 -> d=8; u8 sublane is 32, so one band still carries it
    got = _run_rdma_tiled(img, filt, 1, (1, 1), tile=(32, 128), fuse=4,
                          storage=np.uint8)
    want = oracle.run_serial_u8(img, filt, 4)
    np.testing.assert_array_equal(got, want)


# --- Constraint surface: the fuse guards that replaced the old
# fuse=1-only ValueError.


def test_rdma_fuse_guard_gone():
    # Building a fused RDMA step is now legal (the old guard raised here).
    step._make_block_step(filters.get_filter("blur3"), (2, 2), (16, 16),
                          (8, 8), True, "pallas_rdma", fuse=2)


def test_rdma_fuse_depth_exceeds_block():
    import jax.numpy as jnp

    from parallel_convolution_tpu.ops import pallas_rdma

    with pytest.raises(ValueError, match="ghost depth"):
        pallas_rdma.fused_rdma_step(jnp.zeros((1, 8, 8), jnp.float32),
                                    filters.get_filter("blur3"), (2, 2),
                                    fuse=9, valid_hw=(16, 16))


def test_rdma_tiled_fuse_depth_exceeds_band():
    import jax.numpy as jnp

    from parallel_convolution_tpu.ops import pallas_rdma

    # f32 sublane is 8: r*fuse = 9 live ghosts cannot ride one band
    with pytest.raises(ValueError, match="r\\*fuse"):
        pallas_rdma.fused_rdma_step(jnp.zeros((1, 64, 256), jnp.float32),
                                    filters.get_filter("blur3"), (2, 2),
                                    tiled=True, fuse=9, valid_hw=(128, 512))


def test_rdma_fused_needs_valid_hw():
    import jax.numpy as jnp

    from parallel_convolution_tpu.ops import pallas_rdma

    with pytest.raises(ValueError, match="valid_hw"):
        pallas_rdma.fused_rdma_step(jnp.zeros((1, 32, 32), jnp.float32),
                                    filters.get_filter("blur3"), (2, 2),
                                    fuse=2)
