"""Fused remote-DMA halo kernel: bit-exactness + race-freedom on CPU mesh.

TPU interpret mode simulates remote DMAs, semaphores and per-device
buffers on the virtual CPU mesh, so the cross-device protocol (two-phase
sends, conditional boundary waits, corner propagation) is executed for
real — this is the reference's Isend/Irecv tier moved inside the kernel.
Perf on real multi-chip hardware is explicitly NOT validated here (no
such hardware in this environment); semantics are.
"""

import jax
import numpy as np
import pytest

from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.parallel import mesh as mesh_lib, step
from parallel_convolution_tpu.utils import imageio


def _mesh(shape):
    return mesh_lib.make_grid_mesh(jax.devices()[: shape[0] * shape[1]], shape)


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (2, 4), (4, 1),
                                        (1, 8)])
def test_rdma_bitexact_vs_oracle(grey_odd, mesh_shape):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 4)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 4, mesh=_mesh(mesh_shape),
                               quantize=True, backend="pallas_rdma")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_rdma_rgb_radius2(rgb_odd):
    # radius-2: 2-wide ghost slabs + 2-hop corners through the RDMA path
    filt = filters.get_filter("gaussian5")
    want = oracle.run_serial_u8(rgb_odd, filt, 3)
    x = imageio.interleaved_to_planar(rgb_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 3, mesh=_mesh((2, 2)),
                               quantize=True, backend="pallas_rdma")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_rdma_periodic(grey_small):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_small, filt, 4, boundary="periodic")
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)
    out = step.sharded_iterate(x, filt, 4, mesh=_mesh((2, 2)), quantize=True,
                               backend="pallas_rdma", boundary="periodic")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_rdma_u8_storage(grey_odd):
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(grey_odd, filt, 5)
    x = imageio.interleaved_to_planar(grey_odd).astype(np.float32)
    out = step.sharded_iterate(x, filt, 5, mesh=_mesh((2, 2)), quantize=True,
                               backend="pallas_rdma", storage="u8")
    got = imageio.planar_to_interleaved(np.asarray(out).astype(np.uint8))
    np.testing.assert_array_equal(got, want)


def test_rdma_race_detector(grey_small):
    """The interpreter's vector-clock race detector over the full protocol.

    This is the framework's race-detection tier (SURVEY.md §5 sanitizers):
    local ghost zeroing vs inbound remote writes are disjoint by design,
    and detect_races=True proves it on every (device, phase) pair.
    """
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)[
        :, :24, :36]
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        return pallas_rdma.fused_rdma_step(
            v, filt, (2, 2), "zero", quantize=True, interpret=params)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(x[0].astype(np.uint8), filt, 1)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)


def test_rdma_back_to_back_race(grey_small):
    """≥2 chained invocations under the race detector (cross-invocation fix).

    The iteration driver runs the kernel back-to-back inside a fori_loop;
    the start-of-kernel neighbor barrier must keep a fast device's
    iteration-N+1 remote copies out of a slow neighbor's still-live
    iteration-N scratch.  detect_races=True checks every (device, phase)
    pair across all three chained invocations, and the result must stay
    bit-exact vs three serial oracle steps.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from parallel_convolution_tpu.ops import pallas_rdma
    from parallel_convolution_tpu.parallel.mesh import AXES

    filt = filters.get_filter("blur3")
    mesh = _mesh((2, 2))
    x = imageio.interleaved_to_planar(grey_small).astype(np.float32)[
        :, :24, :36]
    params = pltpu.InterpretParams(dma_execution_mode="on_wait",
                                   detect_races=True)

    def body(v):
        def one(_, cur):
            return pallas_rdma.fused_rdma_step(
                cur, filt, (2, 2), "zero", quantize=True, interpret=params)
        return lax.fori_loop(0, 3, one, v)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(None, *AXES), out_specs=P(None, *AXES),
        check_vma=False,
    ))(x)
    want = oracle.run_serial_u8(x[0].astype(np.uint8), filt, 3)
    np.testing.assert_array_equal(np.asarray(out)[0].astype(np.uint8), want)
    assert jnp.issubdtype(out.dtype, jnp.floating)


def test_collective_id_registry():
    from parallel_convolution_tpu.ops import collective_ids

    assert collective_ids.collective_id("rdma_halo_stencil") == 1
    with pytest.raises(KeyError, match="no collective_id"):
        collective_ids.collective_id("nope")
    # ids must be unique — a collision in the static table is a code bug
    ids = list(collective_ids._COLLECTIVE_IDS.values())
    assert len(ids) == len(set(ids))


def test_rdma_rejects_fuse():
    with pytest.raises(ValueError, match="fuse=1"):
        step._make_block_step(filters.get_filter("blur3"), (2, 2), (8, 8),
                              (4, 4), True, "pallas_rdma", fuse=2)
