"""End-to-end CLI + models tests — the reference's own validation workflow:
serial output vs distributed output must be byte-identical."""

import numpy as np
import pytest

from parallel_convolution_tpu import cli
from parallel_convolution_tpu.models import ConvolutionModel, JacobiSolver
from parallel_convolution_tpu.ops import filters, oracle
from parallel_convolution_tpu.utils import imageio


def test_model_run_image_matches_oracle(rgb_odd):
    m = ConvolutionModel(filt="blur3", backend="shifted")
    got = m.run_image(rgb_odd, 4)
    want = oracle.run_serial_u8(rgb_odd, filters.get_filter("blur3"), 4)
    np.testing.assert_array_equal(got, want)


def test_jacobi_solver(grey_small):
    s = JacobiSolver(tol=0.5, max_iters=200, check_every=5)
    out, iters = s.solve(
        imageio.interleaved_to_planar(grey_small).astype(np.float32)
    )
    assert 0 < iters <= 200
    assert out.shape == (1, *grey_small.shape)


def test_cli_end_to_end_run_vs_serial(tmp_path, capsys):
    # generate → serial → run → compare: the full reference workflow.
    src = str(tmp_path / "in.raw")
    golden = str(tmp_path / "serial.raw")
    out = str(tmp_path / "tpu.raw")

    assert cli.main(["generate", src, "31", "45", "rgb", "--seed", "5"]) == 0
    assert cli.main(["serial", src, "31", "45", "10", "rgb",
                     "-o", golden, "--filter", "blur3"]) == 0
    assert cli.main(["run", src, "31", "45", "10", "rgb", "-o", out,
                     "--filter", "blur3", "--mesh", "2x4"]) == 0
    assert cli.main(["compare", golden, out]) == 0
    assert "identical" in capsys.readouterr().out


def test_cli_compare_differs(tmp_path, capsys):
    a, b = str(tmp_path / "a.raw"), str(tmp_path / "b.raw")
    imageio.write_raw(a, np.zeros((4, 4), np.uint8))
    imageio.write_raw(b, np.ones((4, 4), np.uint8))
    assert cli.main(["compare", a, b]) == 1
    assert "differ: 16 bytes" in capsys.readouterr().out


def test_cli_converge(tmp_path, capsys):
    src = str(tmp_path / "in.raw")
    out = str(tmp_path / "out.raw")
    cli.main(["generate", src, "24", "32", "grey"])
    assert cli.main(["run", src, "24", "32", "500", "grey", "-o", out,
                     "--filter", "blur3", "--converge", "0.5",
                     "--check-every", "5", "--mesh", "2x2"]) == 0
    assert "converged after" in capsys.readouterr().out


def test_cli_tile_flag(tmp_path, capsys):
    """--tile TH,TW reaches the Pallas kernels; output stays golden."""
    src = str(tmp_path / "in.raw")
    a, b = str(tmp_path / "a.raw"), str(tmp_path / "b.raw")
    cli.main(["generate", src, "26", "38", "grey", "--seed", "9"])
    assert cli.main(["serial", src, "26", "38", "6", "grey", "-o", a]) == 0
    assert cli.main(["run", src, "26", "38", "6", "grey", "-o", b,
                     "--mesh", "2x2", "--backend", "pallas_sep",
                     "--fuse", "3", "--tile", "16,128"]) == 0
    assert cli.main(["compare", a, b]) == 0
    with pytest.raises(SystemExit):
        cli.main(["run", src, "26", "38", "6", "grey", "-o", b,
                  "--tile", "16x128"])


def test_cli_bench_subcommand(capsys):
    """`pconv-tpu bench` prints one machine-readable row (C10 via CLI)."""
    import json

    assert cli.main(["bench", "64", "96", "3", "grey", "--mesh", "2x2",
                     "--backend", "pallas_sep", "--fuse", "2",
                     "--tile", "16,128", "--reps", "1"]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["mesh"] == "2x2" and row["gpixels_per_s"] > 0
    assert row["backend"] == "pallas_sep" and row["fuse"] == 2


def test_cli_info(capsys):
    assert cli.main(["info"]) == 0
    out = capsys.readouterr().out
    assert "devices: 8" in out and "blur3" in out


def test_cli_sharded_io_and_checkpoint(tmp_path, capsys):
    src = str(tmp_path / "in.raw")
    a, b, c = (str(tmp_path / n) for n in ("a.raw", "b.raw", "c.raw"))
    cli.main(["generate", src, "30", "44", "grey", "--seed", "6"])
    assert cli.main(["serial", src, "30", "44", "8", "grey", "-o", a]) == 0
    assert cli.main(["run", src, "30", "44", "8", "grey", "-o", b,
                     "--mesh", "2x2", "--sharded-io"]) == 0
    assert cli.main(["compare", a, b]) == 0
    assert cli.main(["run", src, "30", "44", "8", "grey", "-o", c,
                     "--mesh", "2x2", "--checkpoint",
                     str(tmp_path / "ck"), "--checkpoint-every", "3"]) == 0
    assert cli.main(["compare", a, c]) == 0


def test_multihost_single_process_noops():
    from parallel_convolution_tpu.parallel import multihost

    multihost.initialize(num_processes=1)
    info = multihost.process_info()
    assert info["process_count"] == 1 and info["global_devices"] == 8
    multihost.barrier()
    assert multihost.broadcast_scalar(3.5) == 3.5


def test_medium_golden_canonical_aspect():
    # Scaled-down canonical geometry (1920x2520 -> 192x252) with the
    # reference's standard workload shape: blur3, grey, many iterations.
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    import jax

    img = imageio.generate_test_image(192, 252, "grey", seed=77)
    filt = filters.get_filter("blur3")
    want = oracle.run_serial_u8(img, filt, 25)
    m = mesh_lib.make_grid_mesh(jax.devices()[:8], (2, 4))
    model = ConvolutionModel(filt=filt, mesh=m, backend="separable",
                             storage="bf16", fuse=5)
    got = model.run_image(img, 25)
    np.testing.assert_array_equal(got, want)


def test_fast_preset_resolution(monkeypatch):
    # --fast fills only UNSET knobs (argparse None-sentinel: an explicit
    # `--fuse 1` stays unfused), only on a TPU (off-TPU the interpreter
    # would make the "fast" preset the slow one), and clamps fuse to the
    # per-device block so small images never trip the fuse>=block error.
    import argparse

    import jax

    from parallel_convolution_tpu import cli
    from parallel_convolution_tpu.parallel import mesh as mesh_lib
    from parallel_convolution_tpu.utils import platform as plat

    m = mesh_lib.make_grid_mesh(jax.devices()[:4], (2, 2))

    def ns(**kw):
        base = dict(fast=True, backend=None, storage=None, fuse=None,
                    rows=1920, cols=2520, filter_name="blur3")
        base.update(kw)
        return argparse.Namespace(**base)

    monkeypatch.setattr(plat, "on_tpu", lambda: True)
    a = ns()
    cli._resolve_perf_knobs(a, m)
    assert (a.backend, a.storage, a.fuse) == ("pallas_sep", "bf16", 32)

    a = ns(backend="pallas", fuse=1)  # explicit flags always win
    cli._resolve_perf_knobs(a, m)
    assert (a.backend, a.storage, a.fuse) == ("pallas", "bf16", 1)

    a = ns(rows=40, cols=40)  # 20x20 blocks: fuse clamps to the block
    cli._resolve_perf_knobs(a, m)
    assert a.fuse == 20

    a = ns(fast=False)
    cli._resolve_perf_knobs(a, m)
    assert (a.backend, a.storage, a.fuse) == ("shifted", "f32", 1)

    monkeypatch.setattr(plat, "on_tpu", lambda: False)
    a = ns()
    cli._resolve_perf_knobs(a, m)  # off-TPU: normal defaults
    assert (a.backend, a.storage, a.fuse) == ("shifted", "f32", 1)


def test_cli_interior_split_end_to_end(tmp_path):
    # --interior-split through the CLI on a 1x1 mesh, with a geometry wide
    # enough to genuinely split; output must stay byte-identical to serial.
    src = str(tmp_path / "in.raw")
    cli.main(["generate", src, "45", "300", "grey", "--seed", "31"])
    out_a = str(tmp_path / "a.raw")
    out_b = str(tmp_path / "b.raw")
    assert cli.main(["run", src, "45", "300", "6", "grey", "-o", out_a,
                     "--mesh", "1x1", "--backend", "pallas_sep",
                     "--fuse", "3", "--tile", "8,128",
                     "--interior-split"]) == 0
    assert cli.main(["serial", src, "45", "300", "6", "grey",
                     "-o", out_b]) == 0
    assert cli.main(["compare", out_a, out_b]) == 0
